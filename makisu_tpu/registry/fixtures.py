"""Hermetic registry fixtures: the v2 protocol served in-process.

Reference test strategy: lib/registry/pull_fixture.go:23-138 (canned image
through a fake RoundTripper) and push_fixture.go:17-171 (full upload state
machine with per-URL response overrides for fault injection). This is what
makes distributed behavior unit-testable without a registry container.
"""

from __future__ import annotations

import gzip
import io
import json
import re
import tarfile

from makisu_tpu.docker.image import (
    MEDIA_TYPE_CONFIG,
    MEDIA_TYPE_LAYER,
    Descriptor,
    Digest,
    DistributionManifest,
    ImageConfig,
)
from makisu_tpu.utils.httputil import Response, Transport


def make_test_image(files: dict[str, bytes] | None = None,
                    env: list[str] | None = None):
    """Synthesize a one-layer image. Returns (manifest, config_blob,
    {hex: blob})."""
    files = files if files is not None else {"etc/base-release": b"test\n"}
    tar_buf = io.BytesIO()
    with tarfile.open(fileobj=tar_buf, mode="w|") as tw:
        dirs = sorted({n.rsplit("/", 1)[0] for n in files if "/" in n})
        for d in dirs:
            ti = tarfile.TarInfo(d)
            ti.type = tarfile.DIRTYPE
            ti.mode = 0o755
            tw.addfile(ti)
        for name, content in sorted(files.items()):
            ti = tarfile.TarInfo(name)
            ti.size = len(content)
            ti.mode = 0o644
            tw.addfile(ti, io.BytesIO(content))
    tar_bytes = tar_buf.getvalue()
    layer_blob = gzip.compress(tar_bytes, mtime=0)
    config = ImageConfig()
    if isinstance(env, str):
        raise TypeError("env must be a list of KEY=VAL strings")
    config.config.env = env or []
    config.rootfs.diff_ids = [str(Digest.of_bytes(tar_bytes))]
    config_blob = config.to_bytes()
    manifest = DistributionManifest(
        config=Descriptor(MEDIA_TYPE_CONFIG, len(config_blob),
                          Digest.of_bytes(config_blob)),
        layers=[Descriptor(MEDIA_TYPE_LAYER, len(layer_blob),
                           Digest.of_bytes(layer_blob))])
    blobs = {
        Digest.of_bytes(config_blob).hex(): config_blob,
        Digest.of_bytes(layer_blob).hex(): layer_blob,
    }
    return manifest, config_blob, blobs


class RegistryFixture(Transport):
    """In-process registry: blobs/manifests in dicts, full upload state
    machine, per-(method,url-regex) response overrides."""

    def __init__(self, require_token: str = "",
                 strict_media_types: bool = False) -> None:
        super().__init__()
        self.blobs: dict[str, bytes] = {}          # hex → blob
        self.manifests: dict[str, bytes] = {}      # "<repo>:<tag>" → json
        self.uploads: dict[str, bytearray] = {}    # uuid → partial blob
        self.overrides: list[tuple[str, str, Response]] = []
        self.requests: list[tuple[str, str]] = []  # log for assertions
        # Chunk pushes arrive from a thread pool; upload-session ids
        # must not collide under concurrency.
        import itertools
        self._upload_ids = itertools.count()
        # When set, /v2/ endpoints demand "Bearer <require_token>" and
        # 401-challenge to /token (exercises the auth dance).
        self.require_token = require_token
        # Strict registries (policy-enforcing Harbor/quay setups) reject
        # manifests whose layers carry media types they don't know —
        # including this framework's chunk-pin manifests. Tests flip
        # this on to prove builds degrade gracefully instead of failing.
        self.strict_media_types = strict_media_types

    _KNOWN_LAYER_TYPES = (
        MEDIA_TYPE_LAYER,
        "application/vnd.oci.image.layer.v1.tar+gzip",
        "application/vnd.docker.image.rootfs.foreign.diff.tar.gzip",
    )

    # -- test wiring ------------------------------------------------------

    def serve_image(self, repo: str, tag: str, manifest: DistributionManifest,
                    blobs: dict[str, bytes]) -> None:
        self.manifests[f"{repo}:{tag}"] = manifest.to_bytes()
        self.blobs.update(blobs)

    def override(self, method: str, url_pattern: str,
                 response: Response) -> None:
        """Next matching request returns this response (fault injection)."""
        self.overrides.append((method, url_pattern, response))

    def gc(self) -> list[str]:
        """Delete every blob not referenced by any manifest — what real
        registries' garbage collectors do. Returns the deleted digests
        (tests assert pinning kept the right blobs alive)."""
        referenced: set[str] = set()
        for raw in self.manifests.values():
            manifest = json.loads(raw)
            config = manifest.get("config") or {}
            if config.get("digest", "").startswith("sha256:"):
                referenced.add(config["digest"][len("sha256:"):])
            for layer in manifest.get("layers") or []:
                digest = layer.get("digest", "")
                if digest.startswith("sha256:"):
                    referenced.add(digest[len("sha256:"):])
        removed = [h for h in self.blobs if h not in referenced]
        for h in removed:
            del self.blobs[h]
        return removed

    # -- transport --------------------------------------------------------

    def round_trip(self, method, url, headers, body=None, timeout=60.0,
                   stream_to=None):  # fixtures return bytes directly
        self.requests.append((method, url))
        for i, (m, pattern, resp) in enumerate(self.overrides):
            if m == method and re.search(pattern, url):
                del self.overrides[i]
                return resp
        if hasattr(body, "read"):
            body = body.read()
        path = re.sub(r"^https?://[^/]+", "", url)

        if path.startswith("/token"):
            return Response(200, {}, json.dumps(
                {"token": self.require_token}).encode())
        if self.require_token and path.startswith("/v2/"):
            if headers.get("Authorization") != f"Bearer {self.require_token}":
                return Response(401, {
                    "www-authenticate":
                        'Bearer realm="https://registry.test/token",'
                        'service="registry.test",scope="repo:pull"',
                }, b"authentication required")

        m = re.fullmatch(r"/v2/(.+)/manifests/([^/]+)", path)
        if m:
            repo, tag = m.groups()
            key = f"{repo}:{tag}"
            if method == "GET":
                if key in self.manifests:
                    return Response(200, {}, self.manifests[key])
                return Response(404, {}, b"manifest unknown")
            if method == "PUT":
                payload = bytes(body or b"")
                if self.strict_media_types:
                    try:
                        parsed = json.loads(payload)
                    except ValueError:
                        return Response(400, {}, b"MANIFEST_INVALID")
                    bad = [l.get("mediaType")
                           for l in parsed.get("layers") or []
                           if l.get("mediaType")
                           not in self._KNOWN_LAYER_TYPES]
                    if bad:
                        return Response(
                            400, {},
                            json.dumps({"errors": [{
                                "code": "MANIFEST_INVALID",
                                "message": f"unknown layer media "
                                           f"types {bad[:3]}"}]}).encode())
                self.manifests[key] = payload
                return Response(201, {}, b"")
            if method == "HEAD":
                status = 200 if key in self.manifests else 404
                return Response(status, {}, b"")

        m = re.fullmatch(r"/v2/(.+)/blobs/sha256:([0-9a-f]{64})", path)
        if m:
            hex_digest = m.group(2)
            if method == "HEAD":
                return Response(200 if hex_digest in self.blobs else 404,
                                {}, b"")
            if method == "GET":
                if hex_digest in self.blobs:
                    data = self.blobs[hex_digest]
                    rng = headers.get("Range", "")
                    m_rng = re.fullmatch(r"bytes=(\d+)-(\d+)", rng)
                    if m_rng:
                        start = int(m_rng.group(1))
                        end = min(int(m_rng.group(2)) + 1, len(data))
                        if 0 <= start < end:
                            return Response(206, {}, data[start:end])
                    return Response(200, {}, data)
                return Response(404, {}, b"blob unknown")

        m = re.fullmatch(r"/v2/(.+)/blobs/uploads/", path)
        if m and method == "POST":
            uuid = f"upload-{next(self._upload_ids)}"
            self.uploads[uuid] = bytearray()
            return Response(
                202, {"location": f"/v2/{m.group(1)}/blobs/uploads/{uuid}"},
                b"")

        m = re.fullmatch(r"/v2/(.+)/blobs/uploads/([^?]+)(\?digest=(.+))?",
                         path)
        if m:
            repo, uuid, _, digest = m.groups()
            if method == "PATCH":
                if uuid not in self.uploads:
                    return Response(404, {}, b"upload unknown")
                content_range = headers.get("Content-Range", "")
                if content_range:
                    start = int(content_range.split("-")[0])
                    if start != len(self.uploads[uuid]):
                        return Response(416, {}, b"range mismatch")
                self.uploads[uuid].extend(body or b"")
                return Response(
                    202, {"location": f"/v2/{repo}/blobs/uploads/{uuid}"},
                    b"")
            if method == "PUT":
                data = bytes(self.uploads.pop(uuid, b"")) + bytes(body or b"")
                actual = Digest.of_bytes(data)
                if digest and digest != str(actual):
                    return Response(400, {}, b"digest mismatch")
                self.blobs[actual.hex()] = data
                return Response(201, {}, b"")

        return Response(404, {}, f"unhandled {method} {path}".encode())
