"""Registry v2 client (reference: lib/registry/)."""

from makisu_tpu.registry import transfer
from makisu_tpu.registry.client import PullHandle, RegistryClient, new_client
from makisu_tpu.registry.config import (
    RegistryConfig,
    SecurityConfig,
    config_for,
    reset_global_config,
    load_config_map,
    update_global_config,
)
from makisu_tpu.registry.fixtures import RegistryFixture, make_test_image

__all__ = [
    "PullHandle", "RegistryClient", "RegistryConfig", "RegistryFixture",
    "SecurityConfig", "config_for", "make_test_image", "new_client",
    "reset_global_config", "transfer", "update_global_config",
]
