"""Bounded-memory parallel transfer engine for registry wire traffic.

One process-wide engine fronts every blob-plane transfer — base-image
pulls, layer pushes, chunk/pack fetches — so concurrency and memory are
governed globally instead of per call site (the reference bounds
transfers with a per-registry WorkerPool, lib/registry/client.go:111-214;
"Bounded-Memory Parallel Image Pulling for Large Container Images",
PAPERS.md, shows parallel ranged pulls under a global memory budget
beating serial streaming without unbounded host RAM).

Two pools, strictly tiered to make deadlock impossible by construction:

- the **blob pool** runs blob-granular leaf operations (one whole-blob
  pull/push, one pack-run fetch). Blob tasks never submit further blob
  tasks.
- the **part pool** runs the ranged parts a large blob splits into.
  Part tasks are pure leaves.

The **memory budget** bounds bytes simultaneously materialized in RAM
by transfers: every ranged part reserves its length before the request
goes out and releases after its bytes hit the destination file;
streaming whole-blob transfers reserve only their 1MiB read buffer.
The ``makisu_transfer_inflight_bytes`` gauge tracks the reservation
level and can never exceed the configured budget.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Iterable

from makisu_tpu.utils import events
from makisu_tpu.utils import logging as log
from makisu_tpu.utils import metrics

# Monotonic timestamp of the engine's last observable progress (task
# admitted or finished, budget bytes released) — the transfer half of
# the build-progress clock; utils/flightrecorder.py combines it with
# the event bus's half for the stall watchdog and /healthz.
_last_progress = time.monotonic()


def last_progress_monotonic() -> float:
    return _last_progress


def _note_progress() -> None:
    global _last_progress
    _last_progress = time.monotonic()
    # Also stamp the calling build's per-context progress cell (task
    # bodies run under the submitter's copied context): a per-build
    # watchdog must see ITS transfers move, not just the process's.
    events.note_progress()

DEFAULT_CONCURRENCY = 8
DEFAULT_MEMORY_BUDGET = 256 * 1024 * 1024   # bytes in flight across pools
DEFAULT_PART_SIZE = 16 * 1024 * 1024        # ranged-part granularity

# Budget charged by a streaming (non-ranged) transfer: its resident
# footprint is one read buffer, not the blob.
STREAM_RESERVE = 1 << 20


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class MemoryBudget:
    """Counting semaphore over bytes. ``acquire`` blocks until the
    reservation fits; a single reservation larger than the whole budget
    is admitted only alone (it must not deadlock, and refusing it would
    turn an oversized blob into a build failure instead of a serial
    transfer). Deliberately BARGING (condition wait, no arrival
    ordering): a small part must be admittable past a blocked oversized
    reservation, or transfer throughput would head-of-line block — the
    fleet front door, which needs the opposite (FIFO fairness over
    admission slots), uses its own gate (fleet/scheduler._SlotGate)
    instead of this class."""

    def __init__(self, limit: int) -> None:
        self.limit = max(int(limit), 1)
        self._used = 0
        self._cond = threading.Condition()

    @property
    def inflight(self) -> int:
        with self._cond:
            return self._used

    def acquire(self, nbytes: int) -> None:
        nbytes = max(int(nbytes), 0)
        with self._cond:
            while self._used + nbytes > self.limit and self._used > 0:
                self._cond.wait()
            self._used += nbytes
            metrics.gauge_set("makisu_transfer_inflight_bytes",
                              self._used)

    def release(self, nbytes: int) -> None:
        nbytes = max(int(nbytes), 0)
        _note_progress()  # bytes landed: the transfer is moving
        with self._cond:
            self._used = max(self._used - nbytes, 0)
            metrics.gauge_set("makisu_transfer_inflight_bytes",
                              self._used)
            self._cond.notify_all()

    @contextlib.contextmanager
    def reserve(self, nbytes: int):
        self.acquire(nbytes)
        try:
            yield
        finally:
            self.release(nbytes)


class TransferEngine:
    """Shared bounded transfer pools + in-flight-bytes budget."""

    def __init__(self, concurrency_: int | None = None,
                 memory_budget: int | None = None,
                 part_size: int | None = None) -> None:
        self.concurrency = max(concurrency_ or _env_int(
            "MAKISU_TPU_TRANSFER_CONCURRENCY", DEFAULT_CONCURRENCY), 1)
        self.part_size = max(part_size or _env_int(
            "MAKISU_TPU_TRANSFER_PART_MB",
            DEFAULT_PART_SIZE >> 20) << 20, 1 << 20)
        budget = memory_budget or _env_int(
            "MAKISU_TPU_TRANSFER_MEMORY_BUDGET_MB",
            DEFAULT_MEMORY_BUDGET >> 20) << 20
        self.budget = MemoryBudget(budget)
        self._blob_pool = ThreadPoolExecutor(
            self.concurrency, thread_name_prefix="transfer-blob")
        self._part_pool = ThreadPoolExecutor(
            self.concurrency, thread_name_prefix="transfer-part")
        self._depth = 0
        self._depth_lock = threading.Lock()

    # -- queue-depth accounting -------------------------------------------

    def _enter(self) -> None:
        _note_progress()
        with self._depth_lock:
            self._depth += 1
            metrics.gauge_set("makisu_transfer_queue_depth", self._depth)

    def _exit(self) -> None:
        _note_progress()
        with self._depth_lock:
            self._depth = max(self._depth - 1, 0)
            metrics.gauge_set("makisu_transfer_queue_depth", self._depth)

    def snapshot(self) -> dict[str, Any]:
        """In-flight state for diagnostic bundles: how much work (and
        memory) was mid-air when the build died. Deliberately
        LOCK-FREE dirty reads: a signal handler may call this having
        interrupted a frame that holds ``_depth_lock`` or the budget
        condition — int attribute reads are atomic under the GIL and
        a slightly stale value is fine for forensics, a deadlocked
        dying process is not."""
        return {
            "queue_depth": self._depth,
            "inflight_bytes": self.budget._used,
            "budget_limit_bytes": self.budget.limit,
            "concurrency": self.concurrency,
            "part_size_bytes": self.part_size,
            "last_progress_seconds": round(
                time.monotonic() - _last_progress, 3),
        }

    # -- blob-granular API -------------------------------------------------

    def submit(self, fn: Callable, *args: Any) -> Future:
        """Run a blob-granular task on the shared pool, carrying the
        caller's contextvars (build telemetry registry / trace id) like
        ``concurrency.ctx_map`` does. Blob tasks must be leaves: they
        may use the part pool and the budget, never ``submit``/``map``
        (the tier rule that keeps the shared pool deadlock-free)."""
        import contextvars
        ctx = contextvars.copy_context()
        self._enter()
        future = self._blob_pool.submit(ctx.run, fn, *args)
        # Done-callback, not a task-body finally: it fires for
        # cancelled futures too (PullHandle.abandon), so the
        # queue-depth gauge can't leak.
        future.add_done_callback(lambda _: self._exit())
        return future

    def map(self, fn: Callable[[Any], Any], items: Iterable[Any]) -> list:
        """Blocking parallel map of a blob-granular leaf over items."""
        futures = [self.submit(fn, item) for item in items]
        # Collect everything before raising so a failure never leaks
        # still-running siblings past the call.
        results, first_error = [], None
        for f in futures:
            try:
                results.append(f.result())
            except BaseException as e:  # noqa: BLE001 - re-raised below
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error
        return results

    # -- ranged multi-part pulls ------------------------------------------

    def should_split(self, size: int) -> bool:
        return size >= 2 * self.part_size and self.concurrency > 1

    def pull_blob_parts(self, client, digest, size: int,
                        dest_path: str) -> str | None:
        """Download one large blob as concurrent HTTP Range parts
        reassembled at-offset into ``dest_path``. Returns the hex
        sha256 of the reassembled bytes, or None when the caller must
        fall back to a streaming whole-blob GET (Range unsupported
        parts exhausted their retries). A 200 "full" response — the
        server ignored Range — short-circuits: its body IS the blob,
        the remaining parts are cancelled, and nothing is wasted.

        Memory: each in-flight part reserves its length against the
        engine budget before its request is issued, so peak resident
        bytes never exceed the budget no matter how many blobs pull
        concurrently. The first part is a sequential PROBE that
        STREAMS to the destination file: a server that ignores Range
        answers it with the whole blob as one 200, which then flows to
        disk through a 1MiB buffer — never a whole-blob
        materialization in RAM, and never one full copy per concurrent
        part."""
        parts = [(off, min(off + self.part_size, size))
                 for off in range(0, size, self.part_size)]
        # The probe streams: resident bytes are one read buffer, or
        # the whole part when the part is smaller than the buffer.
        with self.budget.reserve(min(STREAM_RESERVE,
                                     parts[0][1] - parts[0][0])):
            probe = client.pull_blob_range_to_file(
                digest, parts[0][0], parts[0][1], dest_path)
        if probe is None:
            return None
        kind, nbytes, sha = probe
        if kind == "full":
            if nbytes != size:
                return None  # truncated 200: the streaming route retries
            if sha:
                return sha
        elif len(parts) > 1:
            done = threading.Event()  # unrecoverable: stop other parts
            fd = os.open(dest_path, os.O_WRONLY)

            def fetch(span: tuple[int, int]) -> bool:
                start, end = span
                for attempt in range(2):
                    if done.is_set():
                        return False
                    # The reservation covers the part bytes from the
                    # moment the request goes out until they are on
                    # disk.
                    with self.budget.reserve(end - start):
                        got = client.pull_blob_range(digest, start, end)
                        if got is not None:
                            part_kind, data = got
                            if part_kind == "full":
                                # The probe got a 206 but this part a
                                # 200: Range semantics are broken here
                                # — degrade to the streaming route.
                                done.set()
                                return False
                            os.pwrite(fd, data, start)
                            return True
                    if attempt == 0:
                        metrics.counter_add(
                            "makisu_transfer_part_retries_total")
                done.set()
                return False

            import contextvars
            try:
                os.ftruncate(fd, size)
                futures = []
                for span in parts[1:]:
                    ctx = contextvars.copy_context()
                    futures.append(
                        self._part_pool.submit(ctx.run, fetch, span))
                # Drain EVERY future before the fd can close: a part
                # failing fast must not leave siblings pwriting into a
                # closed (possibly reused) descriptor.
                ok, first_error = True, None
                for future in futures:
                    try:
                        ok = future.result() and ok
                    except BaseException as e:  # noqa: BLE001
                        done.set()
                        ok = False
                        if first_error is None:
                            first_error = e
                if first_error is not None:
                    raise first_error
                if not ok:
                    return None
            finally:
                os.close(fd)
        import hashlib
        h = hashlib.sha256()
        with open(dest_path, "rb") as f:
            for block in iter(lambda: f.read(1 << 20), b""):
                h.update(block)
        return h.hexdigest()

    def shutdown(self) -> None:
        self._blob_pool.shutdown(wait=True)
        self._part_pool.shutdown(wait=True)


# -- process-global engine --------------------------------------------------

_engine: TransferEngine | None = None
_engine_lock = threading.Lock()


def engine() -> TransferEngine:
    """The process-wide engine, created lazily from the environment
    (``MAKISU_TPU_TRANSFER_CONCURRENCY`` / ``..._MEMORY_BUDGET_MB`` /
    ``..._PART_MB``; the CLI's ``--transfer-*`` flags feed these)."""
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = TransferEngine()
        return _engine


def peek() -> TransferEngine | None:
    """The live engine WITHOUT creating one — diagnostics must not
    spin up transfer pools in a process that never transferred.
    Lock-free on purpose: a signal handler calls this and may have
    interrupted a frame inside engine()/configure() that holds
    ``_engine_lock``; a module-global read is atomic under the GIL."""
    return _engine


def set_engine(new: TransferEngine | None) -> TransferEngine | None:
    """Swap the process engine (tests, benchmarks). Returns the old one
    — the caller owns shutting it down."""
    global _engine
    with _engine_lock:
        old, _engine = _engine, new
        return old


def configure(concurrency_: int = 0, memory_budget_mb: int = 0) -> None:
    """Apply CLI flags. Before the engine exists, flags land in the
    environment so the lazy constructor sees them; after (a worker
    whose later build carries different flags), the budget adjusts in
    place — it is just a limit — while a concurrency change only logs:
    resizing a pool under live transfers is not worth the risk."""
    if concurrency_:
        os.environ["MAKISU_TPU_TRANSFER_CONCURRENCY"] = str(concurrency_)
    if memory_budget_mb:
        os.environ["MAKISU_TPU_TRANSFER_MEMORY_BUDGET_MB"] = \
            str(memory_budget_mb)
    with _engine_lock:
        live = _engine
    if live is None:
        return
    if memory_budget_mb:
        live.budget.limit = max(memory_budget_mb << 20, 1)
    if concurrency_ and concurrency_ != live.concurrency:
        log.warning("transfer engine already running with concurrency "
                    "%d; --transfer-concurrency %d ignored for this "
                    "process", live.concurrency, concurrency_)
