"""Per-registry configuration map.

Reference: lib/registry/config.go (ConfigurationMap[registry][repoRegex]
:33-46, Config fields :49-63, defaults :65-93, YAML/JSON load with $VAR
expansion :113-138) and lib/registry/security (basic auth, TLS, cred
helpers).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re

DEFAULT_CONCURRENCY = 3
DEFAULT_TIMEOUT = 180.0
DEFAULT_RETRIES = 3
DEFAULT_PUSH_RATE = 100 * 1024 * 1024     # bytes/sec token bucket
DEFAULT_PUSH_CHUNK = 50 * 1024 * 1024     # Content-Range chunk; -1 = whole


@dataclasses.dataclass
class SecurityConfig:
    tls_verify: bool = True
    ca_cert: str = ""
    # Mutual-TLS client identity (reference: httputil SendTLS options,
    # lib/registry/security/security.go:79 — enterprise registries that
    # authenticate clients by certificate).
    client_cert: str = ""
    client_key: str = ""
    basic_user: str = ""
    basic_password: str = ""
    cred_helper: str = ""  # docker-credential-<name> executable suffix
    # Cross-origin blob redirects normally use a default public-CA
    # transport (presigned S3/GCS URLs must not see the registry's
    # private CA or mTLS identity). Air-gapped setups whose redirect
    # target shares the registry's private CA set this to reuse the
    # registry transport for redirects.
    trust_redirects: bool = False

    @staticmethod
    def from_json(d: dict) -> "SecurityConfig":
        tls = d.get("tls") or {}
        basic = d.get("basic") or {}
        client = tls.get("client") or {}
        return SecurityConfig(
            tls_verify=not client.get("disabled", False),
            ca_cert=tls.get("ca", {}).get("cert", {}).get("path", ""),
            client_cert=client.get("cert", {}).get("path", ""),
            client_key=client.get("key", {}).get("path", ""),
            basic_user=basic.get("username", ""),
            basic_password=basic.get("password", ""),
            cred_helper=d.get("credsStore", ""),
            trust_redirects=bool(d.get("trust_redirects", False)),
        )


@dataclasses.dataclass
class RegistryConfig:
    concurrency: int = DEFAULT_CONCURRENCY
    timeout: float = DEFAULT_TIMEOUT
    retries: int = DEFAULT_RETRIES
    push_rate: float = DEFAULT_PUSH_RATE
    push_chunk: int = DEFAULT_PUSH_CHUNK
    security: SecurityConfig = dataclasses.field(default_factory=SecurityConfig)

    @staticmethod
    def from_json(d: dict) -> "RegistryConfig":
        return RegistryConfig(
            concurrency=d.get("concurrency", DEFAULT_CONCURRENCY),
            timeout=_seconds(d.get("timeout", DEFAULT_TIMEOUT)),
            retries=d.get("retries", DEFAULT_RETRIES),
            push_rate=d.get("push_rate", DEFAULT_PUSH_RATE),
            push_chunk=d.get("push_chunk", DEFAULT_PUSH_CHUNK),
            security=SecurityConfig.from_json(d.get("security") or {}),
        )


def _seconds(val) -> float:
    if isinstance(val, (int, float)):
        return float(val)
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h)?", str(val))
    if not m:
        raise ValueError(f"bad timeout: {val!r}")
    mult = {"ms": 0.001, "s": 1, "m": 60, "h": 3600, None: 1}[m.group(2)]
    return float(m.group(1)) * mult


# registry → repo-regex → config
ConfigurationMap = dict[str, dict[str, RegistryConfig]]

_global_config: ConfigurationMap = {
    "index.docker.io": {
        ".*": RegistryConfig(
            security=SecurityConfig(tls_verify=True)),
    },
}


def load_config_map(source: str) -> ConfigurationMap:
    """Parse a registry config map from a YAML/JSON file path or an
    inline JSON string, expanding ``$VARS`` from the environment —
    without touching the process-global map (builds in one worker carry
    their own map so concurrent --registry-config flags never race)."""
    if os.path.isfile(source):
        with open(source) as f:
            text = f.read()
    else:
        text = source
    text = os.path.expandvars(text)
    try:
        raw = json.loads(text)
    except ValueError:
        import yaml  # optional; ships with most ML images
        raw = yaml.safe_load(text)
    out: ConfigurationMap = {}
    for registry, repos in (raw or {}).items():
        out[registry] = {
            repo_regex: RegistryConfig.from_json(cfg or {})
            for repo_regex, cfg in repos.items()
        }
    return out


def update_global_config(source: str) -> None:
    """Merge a config map into the process-global default (single-build
    CLI commands: pull/push/diff)."""
    for registry, repos in load_config_map(source).items():
        _global_config.setdefault(registry, {}).update(repos)


def config_for(registry: str, repository: str,
               config_map: ConfigurationMap | None = None) -> RegistryConfig:
    for source in (config_map, _global_config):
        repos = (source or {}).get(registry)
        if repos:
            for pattern, cfg in repos.items():
                if re.fullmatch(pattern, repository):
                    return cfg
    return RegistryConfig()


def reset_global_config() -> None:
    """Testing hook: restore defaults."""
    _global_config.clear()
    _global_config["index.docker.io"] = {
        ".*": RegistryConfig(security=SecurityConfig(tls_verify=True)),
    }
