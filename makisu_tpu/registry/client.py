"""Docker registry v2 client: pull/push of manifests, configs, layers.

Reference: lib/registry/client.go (Client iface :48-57; manifest GET/PUT
:216-289; blob HEAD :495; download pullLayerHelper:301-362; chunked
upload POST→PATCH(Content-Range, rate-limited)→PUT :520-614; backoff
retry pushLayerWithBackoff:375-403; parallel transfers via WorkerPool
bounded by per-registry concurrency :111-214) and lib/registry/security
(token auth via WWW-Authenticate challenge, basic auth).
"""

from __future__ import annotations

import base64
import json
import os
import re
import threading
import time

from makisu_tpu.docker.image import (  # noqa: F401 - re-export surface
    MEDIA_TYPE_MANIFEST_LIST,
    MEDIA_TYPE_OCI_INDEX,
    MEDIA_TYPE_CONFIG,
    MEDIA_TYPE_LAYER,
    MEDIA_TYPE_MANIFEST,
    MEDIA_TYPE_OCI_LAYER,
    MEDIA_TYPE_OCI_MANIFEST,
    Digest,
    DistributionManifest,
    ImageName,
)
from makisu_tpu.registry import transfer
from makisu_tpu.registry.config import RegistryConfig, config_for
from makisu_tpu.storage import ImageStore
from makisu_tpu.utils import events
from makisu_tpu.utils import httputil
from makisu_tpu.utils import logging as log
from makisu_tpu.utils import metrics
from makisu_tpu.utils.httputil import HTTPError, Response, Transport, send


def _sha256_file(path: str) -> str:
    """Streaming sha256 of a file (bounded memory for multi-GB blobs)."""
    import hashlib
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


class _RateLimiter:
    """Token bucket over bytes (reference: PushRate :86-88)."""

    def __init__(self, rate: float) -> None:
        self.rate = rate
        self._allowance = rate
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def wait(self, nbytes: int) -> None:
        if self.rate <= 0:
            return
        with self._lock:
            now = time.monotonic()
            self._allowance = min(
                self.rate, self._allowance + (now - self._last) * self.rate)
            self._last = now
            if self._allowance < nbytes:
                time.sleep((nbytes - self._allowance) / self.rate)
                self._allowance = 0
            else:
                self._allowance -= nbytes


class RegistryClient:
    """One client per (registry, repository)."""

    def __init__(self, store: ImageStore, registry: str, repository: str,
                 config: RegistryConfig | None = None,
                 transport: Transport | None = None,
                 config_map=None) -> None:
        self.store = store
        self.registry = registry
        self.repository = repository
        self.config = config or config_for(registry, repository, config_map)
        sec = self.config.security
        self.transport = transport or Transport(
            tls_verify=sec.tls_verify,
            ca_cert=sec.ca_cert or None,
            # key=None means the key is embedded in the cert PEM (a
            # combination load_cert_chain supports; "" is not).
            client_cert=((sec.client_cert, sec.client_key or None)
                         if sec.client_cert else None))
        self._token: str | None = None
        self._limiter = _RateLimiter(self.config.push_rate)
        # Optional hook (hex digest -> local path) for blobs the build
        # holds only lazily (cache hits whose transfer was deferred):
        # push_layer's existence check usually makes upload unnecessary,
        # and only a registry that actually lacks the blob triggers
        # materialization (chunk reconstitution or cache-registry pull).
        self.materialize_blob = None
        # Cross-origin blob redirects (S3/GCS presigned URLs) use a
        # default public-CA transport: the registry's private CA bundle
        # and mTLS client cert must not apply to the CDN. Air-gapped
        # registries whose redirect target shares the private CA opt
        # back in via security.trust_redirects. An explicitly injected
        # transport (test fixtures, proxy/custom-TLS embedders) owns
        # ALL traffic including redirects — never bypass it onto the
        # real network.
        if transport is not None or sec.trust_redirects:
            self.cdn_transport: Transport = self.transport
        else:
            self.cdn_transport = Transport()

    # -- naming -----------------------------------------------------------

    def _base(self) -> str:
        scheme = "https"
        host = self.registry
        if host.startswith("http://"):
            scheme, host = "http", host[len("http://"):]
        elif host.startswith("https://"):
            host = host[len("https://"):]
        elif host.split(":")[0] in ("localhost", "127.0.0.1"):
            scheme = "http"
        return f"{scheme}://{host}/v2/{self.repository}"

    def _absolute(self, location: str) -> str:
        """Resolve a relative or scheme-relative Location header against
        the registry origin (RFC 3986 allows all three forms)."""
        if location.startswith("http"):
            return location
        base = self._base().split("/v2/")[0]
        if location.startswith("//"):
            # Scheme-relative: different host, registry's scheme.
            return base.split("//")[0] + location
        return base + location

    def _same_origin(self, url: str) -> bool:
        from urllib.parse import urlsplit
        return urlsplit(url).netloc == urlsplit(self._base()).netloc

    def _basic_credentials(self) -> tuple[str, str] | None:
        sec = self.config.security
        if sec.basic_user:
            return sec.basic_user, sec.basic_password
        if sec.cred_helper:
            return self._exec_cred_helper(sec.cred_helper)
        return None

    def _exec_cred_helper(self, helper: str) -> tuple[str, str] | None:
        """docker-credential-<helper> get (reference: security.go:128,
        helpers under /makisu-internal/, :39)."""
        import shutil
        import subprocess
        binary = None
        for cand in (f"/makisu-internal/docker-credential-{helper}",
                     f"docker-credential-{helper}"):
            binary = cand if os.path.isfile(cand) else shutil.which(cand)
            if binary:
                break
        if not binary:
            log.warning("credential helper %s not found", helper)
            return None
        try:
            out = subprocess.run(
                [binary, "get"], input=self.registry.encode(),
                capture_output=True, timeout=30, check=True)
            payload = json.loads(out.stdout)
            return payload.get("Username", ""), payload.get("Secret", "")
        except (OSError, ValueError, subprocess.SubprocessError) as e:
            log.warning("credential helper %s failed: %s", helper, e)
            return None

    def _headers(self, extra: dict[str, str] | None = None) -> dict[str, str]:
        headers = dict(extra or {})
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        else:
            creds = self._basic_credentials()
            if creds is not None:
                cred = base64.b64encode(
                    f"{creds[0]}:{creds[1]}".encode()).decode()
                headers["Authorization"] = f"Basic {cred}"
        return headers

    def _send(self, method: str, url: str,
              headers: dict[str, str] | None = None,
              body: bytes | None = None,
              accepted: tuple[int, ...] = (200,),
              stream_to: str | None = None) -> Response:
        try:
            return send(self.transport, method, url, self._headers(headers),
                        body, accepted, retries=self.config.retries,
                        timeout=self.config.timeout,
                        allow_http_fallback=not
                        self.config.security.tls_verify,
                        stream_to=stream_to)
        except HTTPError as e:
            if e.status == 401 and self._authenticate(e):
                return send(self.transport, method, url,
                            self._headers(headers), body, accepted,
                            retries=self.config.retries,
                            timeout=self.config.timeout,
                            stream_to=stream_to)
            raise

    def _authenticate(self, err: HTTPError) -> bool:
        """Bearer-token dance from a WWW-Authenticate challenge
        (reference: security/basicauth.go:41-89)."""
        # The 401 came back through HTTPError; re-probe the endpoint to
        # read the challenge header.
        probe = self.transport.round_trip(
            "GET", err.url, self._headers({}), None, self.config.timeout)
        challenge = probe.header("www-authenticate")
        if not challenge or not challenge.lower().startswith("bearer"):
            return False
        params = dict(re.findall(r'(\w+)="([^"]*)"', challenge))
        realm = params.get("realm")
        if not realm:
            return False
        query = []
        if params.get("service"):
            query.append(f"service={params['service']}")
        if params.get("scope"):
            query.append(f"scope={params['scope']}")
        url = realm + ("?" + "&".join(query) if query else "")
        headers = {}
        creds = self._basic_credentials()
        if creds is not None:
            cred = base64.b64encode(
                f"{creds[0]}:{creds[1]}".encode()).decode()
            headers["Authorization"] = f"Basic {cred}"
        resp = send(self.transport, "GET", url, headers, accepted=(200,),
                    retries=self.config.retries, timeout=self.config.timeout)
        payload = json.loads(resp.body)
        self._token = payload.get("token") or payload.get("access_token")
        return bool(self._token)

    # -- pull -------------------------------------------------------------

    def pull(self, name: ImageName | str) -> DistributionManifest:
        """Pull manifest + config + all layers into the local store."""
        start = time.time()
        handle = self.start_pull(name)
        handle.wait_all()
        log.info("pulled %s/%s:%s", self.registry, self.repository,
                 handle.tag, duration=time.time() - start)
        return handle.manifest

    def start_pull(self, name: ImageName | str) -> "PullHandle":
        """Begin a pipelined pull: the manifest and config blob
        transfer synchronously (callers need both immediately); layer
        blobs download ahead on the shared transfer engine. The
        returned handle waits per layer — FROM application extracts
        layer k while layers k+1.. are still on the wire — or for
        everything (``wait_all``, which also saves the manifest under
        the image name, completing what ``pull`` promises)."""
        tag = name.tag if isinstance(name, ImageName) else str(name)
        manifest = self.pull_manifest(tag)
        self.pull_layer(manifest.config.digest,
                        size=manifest.config.size)
        eng = transfer.engine()
        futures = {}
        for desc in manifest.layers:
            hex_digest = desc.digest.hex()
            if hex_digest in futures:
                continue  # one transfer per digest, however often it repeats
            futures[hex_digest] = eng.submit(
                self._transfer_pull, desc.digest, desc.size)
        return PullHandle(self, name if isinstance(name, ImageName)
                          else None, tag, manifest, futures)

    def _transfer_pull(self, digest: Digest, size: int) -> str:
        with metrics.span("transfer", op="pull",
                          digest=Digest(digest).hex()[:12], bytes=size):
            return self.pull_layer(digest, size=size)

    def pull_manifest(self, tag: str,
                      _depth: int = 0) -> DistributionManifest:
        resp = self._send(
            "GET", f"{self._base()}/manifests/{tag}",
            headers={"Accept": ", ".join((
                MEDIA_TYPE_MANIFEST, MEDIA_TYPE_OCI_MANIFEST,
                MEDIA_TYPE_MANIFEST_LIST, MEDIA_TYPE_OCI_INDEX))})
        if tag.startswith("sha256:"):
            # Pull-by-digest (FROM image@sha256:...): the returned bytes
            # must hash to the requested digest or the registry lied.
            actual = Digest.of_bytes(resp.body)
            if str(actual) != tag:
                raise ValueError(
                    f"manifest digest mismatch: asked for {tag}, "
                    f"got {actual}")
        parsed = json.loads(resp.body)
        media_type = parsed.get("mediaType", "")
        # Multi-arch index / manifest list (capability the reference
        # lacks — it errors here): select the configured platform and
        # re-pull that manifest BY DIGEST, so the child bytes are
        # digest-verified. mediaType is optional for OCI indexes; the
        # "manifests" fan-out key identifies them regardless.
        if (media_type in (MEDIA_TYPE_MANIFEST_LIST, MEDIA_TYPE_OCI_INDEX)
                or (not media_type and "manifests" in parsed
                    and "config" not in parsed)):
            if _depth >= 2:
                raise ValueError(
                    f"manifest index nesting too deep at {tag}")
            digest = self._select_platform_manifest(parsed, tag)
            return self.pull_manifest(digest, _depth=_depth + 1)
        manifest = DistributionManifest.from_json(parsed)
        if manifest.schema_version != 2:
            raise ValueError(
                f"unsupported manifest schema {manifest.schema_version} "
                f"(only schema2 is supported)")
        if manifest.media_type not in (MEDIA_TYPE_MANIFEST,
                                       MEDIA_TYPE_OCI_MANIFEST):
            raise ValueError(
                f"unsupported manifest type {manifest.media_type!r}")
        if manifest.config is None:
            raise ValueError("manifest has no config descriptor")
        return self._normalize_manifest(manifest)

    def _select_platform_manifest(self, index: dict, tag: str) -> str:
        """Pick the target platform's manifest digest from an index.

        Platform = MAKISU_TPU_PLATFORM ("os/arch[/variant]", default
        linux/amd64 — container images are overwhelmingly amd64-built
        and this host-independent default keeps builds reproducible).
        An exact variant match wins; otherwise the first os/arch match.
        """
        want = os.environ.get("MAKISU_TPU_PLATFORM", "linux/amd64")
        parts = want.split("/")
        want_os, want_arch = parts[0], parts[1] if len(parts) > 1 else ""
        want_variant = parts[2] if len(parts) > 2 else ""
        candidates = []
        for entry in index.get("manifests") or []:
            platform = entry.get("platform") or {}
            if (platform.get("os") == want_os
                    and platform.get("architecture") == want_arch):
                candidates.append((platform.get("variant", ""), entry))
        chosen = None
        for variant, entry in candidates:
            if variant == want_variant:
                chosen = entry
                break
        if (chosen is None and candidates and not want_variant):
            # os/arch requested without a variant: accept the index's
            # sole variant (the common linux/arm64 → arm64/v8 case).
            # An EXPLICIT variant never falls back — substituting v8
            # binaries for a v6 request would only fail at runtime.
            variants = {v for v, _ in candidates}
            if len(variants) == 1:
                chosen = candidates[0][1]
        if chosen is None or not chosen.get("digest"):
            available = sorted({
                "/".join(filter(None, (
                    (e.get("platform") or {}).get("os", "?"),
                    (e.get("platform") or {}).get("architecture", "?"),
                    (e.get("platform") or {}).get("variant", ""))))
                for e in index.get("manifests") or []})
            raise ValueError(
                f"no manifest for platform {want!r} in index {tag} "
                f"(available: {available}; set MAKISU_TPU_PLATFORM)")
        log.info("resolved multi-arch index %s to %s (%s)", tag,
                 chosen["digest"], want)
        return chosen["digest"]

    @staticmethod
    def _normalize_manifest(
            manifest: DistributionManifest) -> DistributionManifest:
        """Rewrite OCI media types to the docker schema2 equivalents —
        byte-identical formats for gzip layers — so descriptors that
        propagate into built images and pushes stay self-consistent.
        zstd layers are accepted when libzstd can decode them (the blob
        is stored and pushed VERBATIM under its own digest and media
        type; only the apply-time inflate differs — tario.gzip_reader
        sniffs the frame magic). Anything else (uncompressed tar, or
        zstd on a host without libzstd) is rejected up front rather
        than failing deep in the build."""
        from makisu_tpu.docker.image import (
            MEDIA_TYPE_LAYER_ZSTD,
            MEDIA_TYPE_OCI_LAYER_ZSTD,
            Descriptor,
        )
        from makisu_tpu.utils import zstdio

        zstd_types = (MEDIA_TYPE_OCI_LAYER_ZSTD, MEDIA_TYPE_LAYER_ZSTD)

        def check_zstd(desc: Descriptor) -> Descriptor:
            if not zstdio.available():
                raise ValueError(
                    f"layer {desc.digest} is zstd-compressed "
                    f"({desc.media_type!r}) but libzstd is not "
                    f"available in this process; install libzstd to "
                    f"pull zstd-published images")
            return desc  # kept verbatim: digest/size/media type all true

        if manifest.media_type == MEDIA_TYPE_MANIFEST:
            unsupported = [l.media_type for l in manifest.layers
                           if l.media_type != MEDIA_TYPE_LAYER
                           and l.media_type not in zstd_types]
            if unsupported:
                raise ValueError(
                    f"unsupported layer media types: {unsupported}")
            for layer in manifest.layers:
                if layer.media_type in zstd_types:
                    check_zstd(layer)
            return manifest

        def fix(desc: Descriptor) -> Descriptor:
            if desc.media_type in zstd_types:
                return check_zstd(desc)
            if desc.media_type != MEDIA_TYPE_OCI_LAYER:
                raise ValueError(
                    f"unsupported layer media type {desc.media_type!r} "
                    "(only gzip and zstd tar layers are supported)")
            return Descriptor(MEDIA_TYPE_LAYER, desc.size, desc.digest)
        return DistributionManifest(
            schema_version=2,
            media_type=MEDIA_TYPE_MANIFEST,
            config=Descriptor(MEDIA_TYPE_CONFIG, manifest.config.size,
                              manifest.config.digest),
            layers=[fix(l) for l in manifest.layers])

    def pull_layer(self, digest: Digest, size: int = 0) -> str:
        """Download one blob into the CAS store (no-op if present).

        A blob whose known ``size`` crosses the transfer engine's split
        threshold downloads as concurrent HTTP Range parts reassembled
        at-offset (falling back to one streamed GET when the server
        ignores Range); everything else streams to a sandbox file in
        1MiB chunks — layer blobs can be multi-GB (reference
        pullLayerHelper:301-362 also streams to a download file before
        committing to the CAS). Either way the downloaded bytes are
        verified against the requested digest before the CAS link
        (reference client.go:288-289, saveLayer verify :620-627) — a
        corrupt/truncated/tampered response must never be stored under
        a trusted digest name."""
        import tempfile
        hex_digest = Digest(digest).hex()
        if self.store.layers.exists(hex_digest):
            return self.store.layers.path(hex_digest)
        fd, tmp = tempfile.mkstemp(prefix="blob-")
        os.close(fd)
        try:
            actual = None
            eng = transfer.engine()
            if size and eng.should_split(size):
                actual = eng.pull_blob_parts(self, digest, size, tmp)
            # Ranged parts already counted their bytes per request in
            # _ranged_blob_get; only the streaming route's bytes are
            # uncounted so far.
            streamed = actual is None
            if actual is None:
                # The streaming route's resident footprint is one read
                # buffer; reserve that, not the blob.
                with eng.budget.reserve(transfer.STREAM_RESERVE):
                    resp = self._get_blob_following_redirects(
                        digest, accepted=(200,), stream_to=tmp)
                if resp.status == 200 and resp.body:
                    # Transport without streaming support (fixtures).
                    with open(tmp, "wb") as f:
                        f.write(resp.body)
                # Prefer the hash computed while the bytes streamed in;
                # only non-streaming transports cost a re-read of tmp.
                if resp.stream_sha256:
                    actual = resp.stream_sha256
                elif resp.body:
                    import hashlib
                    actual = hashlib.sha256(resp.body).hexdigest()
                else:
                    actual = _sha256_file(tmp)
            # Bytes crossed the wire whether or not the digest checks
            # out — count before the mismatch raise.
            if streamed:
                metrics.counter_add(metrics.REGISTRY_BYTES_TOTAL,
                                    os.path.getsize(tmp),
                                    direction="pull")
            if actual != hex_digest:
                raise ValueError(
                    f"pulled blob digest mismatch for {digest}: "
                    f"got sha256:{actual}")
            metrics.counter_add(metrics.REGISTRY_BLOBS_TOTAL,
                                direction="pull")
            events.emit("registry_blob", direction="pull",
                        digest=hex_digest,
                        bytes=os.path.getsize(tmp),
                        registry=self.registry)
            return self.store.layers.link_file(hex_digest, tmp)
        finally:
            os.unlink(tmp)

    def pull_image_config(self, digest: Digest) -> bytes:
        path = self.pull_layer(digest)
        with open(path, "rb") as f:
            return f.read()

    def _get_blob_following_redirects(self, digest: Digest,
                                      accepted: tuple[int, ...],
                                      headers: dict[str, str]
                                      | None = None,
                                      stream_to: str | None = None):
        """THE blob-GET redirect chase, shared by whole-blob and ranged
        pulls so the two can't drift. Docker Hub / S3 / GCS-backed
        registries offload blob GETs through redirects, and chains of
        more than one hop happen in the wild (distribution behind CDN
        fronting: 302 → 302 → 200), so loop with a bound rather than
        following exactly one Location. A redirect response's own body
        is never consulted: it is an HTML stub (Go's http.Redirect
        writes one for GET) and must not clobber the blob. Same-origin
        hops keep auth (and the 401 token dance); cross-origin
        presigned URLs (S3/GCS) go through cdn_transport with no
        registry credentials — forwarding them would leak them."""
        redirects = (301, 302, 303, 307, 308)
        resp = self._send("GET", f"{self._base()}/blobs/{digest}",
                          headers=headers,
                          accepted=accepted + redirects,
                          stream_to=stream_to)
        current = f"{self._base()}/blobs/{digest}"
        hops = 0
        while resp.status in redirects:
            hops += 1
            if hops > 5:
                raise ValueError(
                    f"blob {digest}: more than 5 redirect hops")
            # Relative Locations resolve against the hop that issued
            # them (a CDN's relative redirect must not bounce back to
            # the registry origin).
            from urllib.parse import urljoin
            location = urljoin(current, resp.header("location"))
            current = location
            if self._same_origin(location):
                resp = self._send("GET", location, headers=headers,
                                  accepted=accepted + redirects,
                                  stream_to=stream_to)
            else:
                resp = send(self.cdn_transport, "GET", location,
                            dict(headers or {}),
                            retries=self.config.retries,
                            timeout=self.config.timeout,
                            stream_to=stream_to,
                            accepted=accepted + redirects)
        return resp

    def _ranged_blob_get(self, digest: Digest, start: int, end: int,
                         stream_to: str | None) -> Response | None:
        """THE Range-GET core shared by the in-memory and streaming
        variants so the protocol logic can't drift: redirect-chased GET
        with a Range header, transfer-byte accounting, and 206 length
        validation. Returns the Response (status 200 or 206) or None
        on failure/truncation."""
        try:
            resp = self._get_blob_following_redirects(
                digest, accepted=(200, 206),
                headers={"Range": f"bytes={start}-{end - 1}"},
                stream_to=stream_to)
        except Exception as e:  # noqa: BLE001 - range is an optimization
            log.debug("ranged blob GET %s [%d,%d) failed: %s", digest,
                      start, end, e)
            return None
        nbytes = len(resp.body)
        if not resp.body and stream_to is not None:
            nbytes = os.path.getsize(stream_to)
        # Count before the length check: truncated bodies still
        # crossed the wire, and failure episodes are exactly when
        # transfer volume matters.
        metrics.counter_add(metrics.REGISTRY_BYTES_TOTAL, nbytes,
                            direction="pull")
        if resp.status == 206 and nbytes != end - start:
            return None
        return resp

    def pull_blob_range(self, digest: Digest, start: int,
                        end: int) -> tuple[str, bytes] | None:
        """GET bytes [start, end) of a blob via an HTTP Range request
        (chunk-pack consumers fetch only the novel spans of a pack, not
        the whole blob). Returns ("partial", range_bytes) on 206,
        ("full", whole_blob) when the server ignored the Range and sent
        200 (the caller carves what it needs and wastes nothing), or
        None on failure — callers fall back to a whole-blob pull, so a
        registry without Range support degrades in bytes, not in
        correctness. No CAS involvement: a range has no digest of its
        own to verify, so callers MUST verify whatever they carve out
        against content digests before storing it (chunks.py does)."""
        resp = self._ranged_blob_get(digest, start, end, stream_to=None)
        if resp is None:
            return None
        return ("partial" if resp.status == 206 else "full"), resp.body

    def pull_blob_range_to_file(self, digest: Digest, start: int,
                                end: int, path: str):
        """Streaming sibling of :meth:`pull_blob_range`, used for the
        transfer engine's probe part: the 206 range bytes — or the
        WHOLE blob, when the server ignored Range and answered 200 —
        stream to ``path`` in 1MiB chunks, so a Range-less server
        costs disk writes, never a whole multi-GB blob in RAM.
        Returns ``(kind, nbytes_written, stream_sha256 or "")`` with
        kind ``"partial"``/``"full"``, or None on failure."""
        resp = self._ranged_blob_get(digest, start, end, stream_to=path)
        if resp is None:
            return None
        sha = resp.stream_sha256
        if resp.body:
            # Transport without streaming support (fixtures).
            with open(path, "wb") as f:
                f.write(resp.body)
            import hashlib
            sha = hashlib.sha256(resp.body).hexdigest()
        return (("partial" if resp.status == 206 else "full"),
                os.path.getsize(path), sha)

    # -- push -------------------------------------------------------------

    def push(self, name: ImageName | str) -> None:
        tag = name.tag if isinstance(name, ImageName) else str(name)
        manifest = self.store.manifests.load(
            name if isinstance(name, ImageName)
            else ImageName("", self.repository, tag))
        digests = {manifest.config.digest}
        digests.update(manifest.layer_digests())
        start = time.time()
        with metrics.span("registry_push", registry=self.registry,
                          repository=self.repository, tag=tag):
            transfer.engine().map(self._transfer_push, sorted(
                digests, key=str))
            self.push_manifest(tag, manifest)
        log.info("pushed %s/%s:%s", self.registry, self.repository, tag,
                 duration=time.time() - start)

    def push_manifest(self, tag: str, manifest: DistributionManifest) -> None:
        self._send("PUT", f"{self._base()}/manifests/{tag}",
                   headers={"Content-Type": MEDIA_TYPE_MANIFEST},
                   body=manifest.to_bytes(), accepted=(201, 200))

    def layer_exists(self, digest: Digest) -> bool:
        try:
            self._send("HEAD", f"{self._base()}/blobs/{digest}",
                       accepted=(200,))
            return True
        except HTTPError as e:
            if e.status == 404:
                return False
            raise

    def _transfer_push(self, digest: Digest) -> None:
        with metrics.span("transfer", op="push",
                          digest=Digest(digest).hex()[:12]):
            self.push_layer(digest)

    def push_layer(self, digest: Digest) -> None:
        """Blob upload with existence check, chunked PATCH flow, and
        exponential backoff on 5xx (reference :375-466)."""
        digest = Digest(digest)
        if self.layer_exists(digest):
            return
        backoff = 0.5
        for attempt in range(self.config.retries):
            try:
                self._push_layer_content(digest)
                return
            except HTTPError as e:
                if e.status < 500 or attempt == self.config.retries - 1:
                    raise
                metrics.counter_add(metrics.REGISTRY_RETRIES_TOTAL,
                                    op="push_layer")
                time.sleep(backoff)
                backoff *= 2

    # Blobs at or under this size upload monolithically: POST a session,
    # then one PUT?digest= carrying the whole body — 2 round trips
    # instead of 3+ (spec "monolithic upload"; every distribution
    # implementation supports it). Chunk-granular dedup pushes THOUSANDS
    # of small chunk blobs per layer, so per-blob round trips are the
    # dominant cost there, not bytes.
    MONOLITHIC_MAX = 1 << 20

    def _push_layer_content(self, digest: Digest) -> None:
        if (not self.store.layers.exists(digest.hex())
                and self.materialize_blob is not None):
            self.materialize_blob(digest.hex())
        resp = self._send("POST", f"{self._base()}/blobs/uploads/",
                          accepted=(202,))
        location = self._absolute(resp.header("location"))
        chunk = self.config.push_chunk
        path = self.store.layers.path(digest.hex())
        size = os.path.getsize(path)
        budget = transfer.engine().budget
        if size <= self.MONOLITHIC_MAX and (chunk <= 0 or chunk >= size):
            with budget.reserve(size):
                with open(path, "rb") as f:
                    body = f.read()
                self._limiter.wait(len(body))
                sep = "&" if "?" in location else "?"
                # Bytes-pushed counts the attempt (the body goes on the
                # wire before a failure status comes back); blobs-pushed
                # counts completions.
                metrics.counter_add(metrics.REGISTRY_BYTES_TOTAL,
                                    len(body), direction="push")
                self._send("PUT", f"{location}{sep}digest={digest}",
                           headers={"Content-Type":
                                    "application/octet-stream",
                                    "Content-Length": str(len(body))},
                           body=body, accepted=(201, 204))
            metrics.counter_add(metrics.REGISTRY_BLOBS_TOTAL,
                                direction="push")
            events.emit("registry_blob", direction="push",
                        digest=digest.hex(), bytes=len(body),
                        registry=self.registry)
            return
        step = size if (chunk <= 0 or chunk >= size) else chunk
        with open(path, "rb") as f:
            off = 0
            while off < size:
                # One chunk resident at a time, and that residency is
                # charged against the global transfer budget so N
                # parallel pushes can't stack N chunks unboundedly.
                with budget.reserve(min(step, size - off)):
                    piece = f.read(step)
                    self._limiter.wait(len(piece))
                    metrics.counter_add(metrics.REGISTRY_BYTES_TOTAL,
                                        len(piece), direction="push")
                    resp = self._send(
                        "PATCH", location,
                        headers={
                            "Content-Type": "application/octet-stream",
                            "Content-Range":
                                f"{off}-{off + len(piece) - 1}",
                            "Content-Length": str(len(piece)),
                        },
                        body=piece, accepted=(202,))
                off += len(piece)
                location = self._absolute(
                    resp.header("location") or location)
        sep = "&" if "?" in location else "?"
        self._send("PUT", f"{location}{sep}digest={digest}",
                   accepted=(201, 204))
        metrics.counter_add(metrics.REGISTRY_BLOBS_TOTAL,
                            direction="push")
        events.emit("registry_blob", direction="push",
                    digest=digest.hex(), bytes=size,
                    registry=self.registry)


class PullHandle:
    """In-flight pipelined pull: one future per distinct blob digest.

    ``wait_layer`` gates extraction on a single layer (the pipelining
    seam FROM application uses); ``wait_all`` joins every download and
    then saves the manifest under the image name — the manifest must
    never be visible in the local store before all of its blobs are,
    or a concurrent build would trust a manifest whose layers 404
    locally."""

    def __init__(self, client: "RegistryClient",
                 name: "ImageName | None", tag: str,
                 manifest: DistributionManifest, futures: dict) -> None:
        self._client = client
        self._name = name
        self.tag = tag
        self.manifest = manifest
        self._futures = futures
        self._finished = False

    def wait_layer(self, digest: Digest) -> str:
        """Block until one blob is in the local store; returns its
        path. Unknown digests (the config blob, pulled eagerly) just
        resolve through the store."""
        future = self._futures.get(Digest(digest).hex())
        if future is not None:
            return future.result()
        return self._client.store.layers.path(Digest(digest).hex())

    def wait_all(self) -> DistributionManifest:
        if not self._finished:
            first_error = None
            for future in self._futures.values():
                try:
                    future.result()
                except BaseException as e:  # noqa: BLE001 - re-raised
                    if first_error is None:
                        first_error = e
            if first_error is not None:
                raise first_error
            if self._name is not None:
                self._client.store.manifests.save(self._name,
                                                  self.manifest)
            self._finished = True
        return self.manifest

    def abandon(self) -> None:
        """The consumer failed mid-pull: cancel everything still
        queued, join what is already running, and swallow download
        errors — the build's original failure must not be masked, and
        a failed build must not keep eating the engine capacity other
        builds share."""
        for future in self._futures.values():
            future.cancel()
        for future in self._futures.values():
            if not future.cancelled():
                try:
                    future.result()
                except BaseException:  # noqa: BLE001 - best-effort drain
                    pass


# Test seam: when set, new_client routes through this factory instead of
# real HTTP (lets the pull/push/diff CLI commands run against fixtures).
_transport_factory: "Callable[[ImageName], Transport] | None" = None


def set_transport_factory(factory) -> None:
    global _transport_factory
    _transport_factory = factory


def new_client(store: ImageStore, name: ImageName,
               transport: Transport | None = None,
               config_map=None) -> RegistryClient:
    if transport is None and _transport_factory is not None:
        transport = _transport_factory(name)
    return RegistryClient(store, name.registry or "index.docker.io",
                          name.repository, transport=transport,
                          config_map=config_map)
