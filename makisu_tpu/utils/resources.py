"""Process resource sampling: RSS, CPU time, fds, I/O — attributed to
open spans.

The telemetry so far (metrics.py spans, events.py) explains where a
build's *time* went; this module explains where its *memory and CPU*
went, and — through the flight recorder — what the process looked like
right before it died. One daemon sampler thread per process:

- publishes process gauges into the global registry
  (``makisu_process_rss_bytes``, ``makisu_process_cpu_seconds``,
  ``makisu_process_open_fds``, ``makisu_process_threads``,
  ``makisu_process_io_read_bytes`` / ``_write_bytes``) — what the
  worker's ``/metrics`` scrape sees;
- attributes each sample to the currently-open spans
  (``metrics.attribute_resource_sample``): every open span tracks its
  peak RSS, and the CPU burned between samples is charged to the open
  *leaf* spans (split evenly across concurrent leaves), so
  ``makisu-tpu report`` can print peak-RSS/CPU per build phase;
- keeps a bounded recent trajectory (:func:`trajectory`) that the
  flight recorder folds into diagnostic bundles — the "was RSS
  climbing toward the OOM?" record.

Readings come straight from ``/proc/self`` (stdlib-only, no psutil);
on hosts without procfs every field degrades to what ``os.times`` and
``resource.getrusage`` can supply rather than failing. Sampling must
never fail a build: the loop swallows per-tick errors and keeps going.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any

from makisu_tpu.utils import metrics

DEFAULT_INTERVAL = 0.5          # seconds between samples
TRAJECTORY_KEEP = 240           # recent samples kept for bundles (~2min)

_PAGE_SIZE = 4096
try:
    _PAGE_SIZE = os.sysconf("SC_PAGE_SIZE")
except (ValueError, OSError, AttributeError):  # pragma: no cover
    pass


def _rss_bytes() -> int:
    """Current resident set size. ``/proc/self/statm`` field 2 is
    resident pages; the fallback (no procfs) is ru_maxrss — a PEAK,
    but better than nothing on non-Linux dev hosts."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        try:  # pragma: no cover - non-procfs fallback
            import resource as _resource
            return _resource.getrusage(
                _resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:  # noqa: BLE001
            return 0


def _open_fds() -> int | None:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:  # pragma: no cover - no procfs
        return None


def _proc_io() -> dict[str, int]:
    """``/proc/self/io`` read_bytes/write_bytes (actual storage I/O).
    May be absent (no procfs) or unreadable (hardened kernels)."""
    out: dict[str, int] = {}
    try:
        with open("/proc/self/io", "rb") as f:
            for line in f:
                key, _, value = line.partition(b":")
                if key in (b"read_bytes", b"write_bytes"):
                    try:
                        out[key.decode()] = int(value)
                    except ValueError:
                        pass
    except OSError:
        pass
    return out


def read_sample() -> dict[str, Any]:
    """One point-in-time resource sample (JSON-ready)."""
    times = os.times()
    sample: dict[str, Any] = {
        "ts": round(time.time(), 6),
        "rss_bytes": _rss_bytes(),
        "cpu_seconds": round(times.user + times.system, 6),
        "threads": threading.active_count(),
    }
    fds = _open_fds()
    if fds is not None:
        sample["open_fds"] = fds
    io = _proc_io()
    if io:
        sample["io_read_bytes"] = io.get("read_bytes", 0)
        sample["io_write_bytes"] = io.get("write_bytes", 0)
    return sample


class ResourceSampler:
    """Background sampler; one per process (see :func:`ensure_started`).

    The trajectory deque is appended lock-free (``deque(maxlen=...)``
    appends are atomic) so the flight recorder can read it from a
    signal handler without any lock-ordering risk."""

    def __init__(self, interval: float = DEFAULT_INTERVAL) -> None:
        self.interval = max(float(interval), 0.05)
        self._trajectory: "collections.deque[dict]" = \
            collections.deque(maxlen=TRAJECTORY_KEEP)
        self._last_cpu: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def sample_once(self) -> dict[str, Any]:
        """Take one sample: record it, publish gauges, attribute to
        open spans. Split out of the loop so tests (and the flight
        recorder's dump path) can sample deterministically."""
        sample = read_sample()
        self._trajectory.append(sample)
        g = metrics.global_registry()
        g.gauge_set(metrics.PROCESS_RSS_BYTES, sample["rss_bytes"])
        g.gauge_set(metrics.PROCESS_CPU_SECONDS, sample["cpu_seconds"])
        g.gauge_set(metrics.PROCESS_THREADS, sample["threads"])
        if "open_fds" in sample:
            g.gauge_set(metrics.PROCESS_OPEN_FDS, sample["open_fds"])
        if "io_read_bytes" in sample:
            g.gauge_set(metrics.PROCESS_IO_READ_BYTES,
                        sample["io_read_bytes"])
            g.gauge_set(metrics.PROCESS_IO_WRITE_BYTES,
                        sample["io_write_bytes"])
        cpu_delta = 0.0
        if self._last_cpu is not None:
            cpu_delta = max(sample["cpu_seconds"] - self._last_cpu, 0.0)
        self._last_cpu = sample["cpu_seconds"]
        metrics.attribute_resource_sample(sample["rss_bytes"], cpu_delta)
        return sample

    def trajectory(self) -> list[dict]:
        # Race-retried, not locked: the flight recorder reads this
        # from signal handlers while the sampler thread appends.
        return metrics.snapshot_concurrent(self._trajectory)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - sampling never fails a build
                pass

    def start(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="resource-sampler", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


# -- process singleton ------------------------------------------------------

_sampler: ResourceSampler | None = None
_sampler_lock = threading.Lock()


def ensure_started(interval: float | None = None) -> ResourceSampler:
    """Start (or return) the process sampler. Interval resolution:
    explicit argument, then ``MAKISU_TPU_RESOURCE_INTERVAL`` seconds,
    then the 0.5s default. Idempotent — the CLI calls it on every
    invocation and a worker's many builds share one thread."""
    global _sampler
    with _sampler_lock:
        if _sampler is None:
            if interval is None:
                try:
                    interval = float(os.environ.get(
                        "MAKISU_TPU_RESOURCE_INTERVAL", "") or
                        DEFAULT_INTERVAL)
                except ValueError:
                    interval = DEFAULT_INTERVAL
            _sampler = ResourceSampler(interval)
        _sampler.start()
        return _sampler


def trajectory() -> list[dict]:
    """Recent samples (empty when the sampler never started) — the
    resource-trajectory section of diagnostic bundles. Reads the
    singleton WITHOUT ``_sampler_lock``: a signal handler may have
    interrupted ``ensure_started``/``stop`` mid-hold, and a stale
    module-global read (atomic under the GIL) is harmless here."""
    sampler = _sampler
    return sampler.trajectory() if sampler is not None else []


def stop() -> None:
    """Stop the process sampler (tests)."""
    with _sampler_lock:
        if _sampler is not None:
            _sampler.stop()
