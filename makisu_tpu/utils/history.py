"""Build-history store: one compact JSONL record per build, durable
across processes — the first persistent perf-trajectory artifact.

Every observability layer so far (metrics, events, traces, ledger,
forensics) describes ONE build and dies with its files. A fleet needs
the trajectory: is the warm rebuild getting slower, did the cache
ratio regress after that refactor, which ISA route was this host on
when the number moved. This module is that record.

- ``--history-out FILE`` (or ``$MAKISU_TPU_HISTORY_DIR``, which
  resolves to ``<dir>/history.jsonl``) makes ``cli.main`` append one
  record per build/pull/push invocation: schema
  ``makisu-tpu.history.v1``, wall duration, phase self-times (via
  ``traceexport.phase_totals``), cache economics, bytes hashed per
  backend, the native ISA route, backend/mode identity, exit code.
  Appends are a single ``O_APPEND`` write per record, so concurrent
  builds (a loadgen run, parallel CI jobs) can share one file without
  interleaving partial lines.
- ``makisu-tpu history PATH...`` renders the trend: per-record rows
  plus duration/cache aggregates (p50/p99 via ``metrics.percentile``).
- ``makisu-tpu history diff A B`` compares two history sets and FLAGS
  regressions beyond ``--threshold`` (default 25%): duration p50/p99
  growth, cache hit-ratio and chunk dedup-ratio drops. Exit code 1
  when a regression is flagged — wired into CI as a perf gate.

Like the rest of the telemetry layer: stdlib-only, and never able to
fail a build (``cli.main`` guards the append).
"""

from __future__ import annotations

import contextvars
import json
import os
import sys
import time
from typing import Any

from makisu_tpu.utils import events, metrics

HISTORY_SCHEMA = "makisu-tpu.history.v1"

# Default filename inside $MAKISU_TPU_HISTORY_DIR.
HISTORY_BASENAME = "history.jsonl"

# Regression gate metrics: (key, direction). "up" flags growth beyond
# the threshold (latencies); "down" flags shrinkage (ratios where
# bigger is better).
_GATES: tuple[tuple[str, str], ...] = (
    ("duration_p50", "up"),
    ("duration_p99", "up"),
    ("cache_hit_ratio", "down"),
    ("chunk_dedup_ratio", "down"),
)


# Fleet provenance for the NEXT record this context appends: the
# worker binds it (from the front door's forwarded routing outcome)
# around each /build it serves, so a build that arrived via the fleet
# records WHERE it ran and WHY it was routed there — the signal
# `history diff` needs to attribute a latency swing to a routing-mix
# change instead of a code change.
_fleet_provenance: "contextvars.ContextVar[dict | None]" = \
    contextvars.ContextVar("makisu_history_fleet", default=None)


def bind_fleet_provenance(info: dict):
    """Bind this build's fleet routing provenance (worker socket,
    verdict, attempts, quota wait) in the current context. Returns a
    reset token."""
    return _fleet_provenance.set(dict(info))


def reset_fleet_provenance(token) -> None:
    _fleet_provenance.reset(token)


def fleet_provenance() -> dict | None:
    return _fleet_provenance.get()


def resolve_out(flag: str) -> str:
    """The history path this invocation appends to: the explicit
    ``--history-out`` file wins; else ``$MAKISU_TPU_HISTORY_DIR/
    history.jsonl``; else "" (history off)."""
    if flag:
        return flag
    history_dir = os.environ.get("MAKISU_TPU_HISTORY_DIR", "")
    if history_dir:
        return os.path.join(history_dir, HISTORY_BASENAME)
    return ""


def probe_label() -> str:
    """This process's device-route probe verdict for the history
    record: ``ok`` | ``wedged`` | ``failed`` | ``pending`` |
    ``absent`` | ``disabled``. Resolved WITHOUT importing the device
    stack: if ``ops.backend`` was never imported, no probe ran —
    that's ``absent`` — and a pull/push must not pay a jax import for
    a telemetry label."""
    mod = sys.modules.get("makisu_tpu.ops.backend")
    if mod is None:
        return "absent"
    try:
        return str(mod.probe_label())
    except Exception:  # noqa: BLE001 - a label must never fail a build
        return "absent"


def warm_mode_label() -> str:
    """This build's residency state for the history record:
    ``resident`` (session reused, dirty-set incremental), ``rescan``
    (session reused but re-certifying), ``fresh`` (new session),
    ``off`` (sessions disabled/bypassed), ``none`` (no build ran).
    Resolved via sys.modules like :func:`probe_label`: if the session
    module never loaded, no session engaged."""
    mod = sys.modules.get("makisu_tpu.worker.session")
    if mod is None:
        return "none"
    try:
        return str(mod.warm_mode())
    except Exception:  # noqa: BLE001 - a label must never fail a build
        return "none"


def record_from_report(report: dict, command: str = "",
                       exit_code: int = 0,
                       **extra: Any) -> dict:
    """Distill one build's ``--metrics-out``-shaped report into the
    compact history record. Everything here is derived from series the
    registry already carries — history adds durability, not new
    instrumentation."""
    from makisu_tpu.utils import traceexport
    top = traceexport.root_span(report)
    duration = float((top or {}).get("duration") or 0.0)
    cache = traceexport.cache_stats(report)
    hashed = traceexport.bytes_hashed_by_backend(report)
    chunk_added = chunk_reused = 0.0
    for series in (report.get("counters") or {}).get(
            "makisu_chunk_bytes_total", []):
        value = float(series.get("value", 0.0))
        if series.get("labels", {}).get("result") == "added":
            chunk_added += value
        elif series.get("labels", {}).get("result") == "reused":
            chunk_reused += value
    chunk_total = chunk_added + chunk_reused
    info_labels: dict = {}
    for series in (report.get("gauges") or {}).get(
            "makisu_build_info", []):
        info_labels = series.get("labels", {})
        break
    record = {
        "schema": HISTORY_SCHEMA,
        "ts": round(time.time(), 3),
        "trace_id": report.get("trace_id", ""),
        "command": command or report.get("command", ""),
        "exit_code": exit_code,
        "duration_seconds": round(duration, 6),
        "phase_self_seconds": {
            phase: round(seconds, 6)
            for phase, seconds in
            traceexport.phase_totals(report).items() if seconds},
        "cache": {
            "hits": int(cache["hit"]),
            "misses": int(cache["miss"]),
            "hit_ratio": round(cache["ratio"], 4),
            "chunk_bytes_added": int(chunk_added),
            "chunk_bytes_reused": int(chunk_reused),
            "chunk_dedup_ratio": round(chunk_reused / chunk_total, 4)
            if chunk_total else 0.0,
        },
        "bytes_hashed": {backend: int(n)
                         for backend, n in sorted(hashed.items())},
        "backend": info_labels.get("platform", ""),
        "native_isa": info_labels.get("native_isa", ""),
        "mode": info_labels.get("mode", ""),
        "hasher": info_labels.get("hasher", ""),
        # Device-route state during this build: `history diff` uses it
        # to attribute latency swings to route changes (a build whose
        # chunk hashing degraded to whole-layer caching because the
        # backend wedged is slower for reasons no code change made).
        "device_probe": probe_label(),
        # Residency state: a latency swing between `resident` and
        # `off`/`rescan` records is warm-state economics, not a code
        # regression — `history diff` names the change.
        "warm_mode": warm_mode_label(),
    }
    # Fleet provenance (bound by the worker when a build arrived via
    # the front door): worker socket + routing verdict + attempt count
    # + front-door quota wait. Absent on direct builds — its presence
    # IS the route label the routing-mix aggregate counts.
    fleet = fleet_provenance()
    if fleet is not None:
        record["fleet"] = dict(fleet)
    record.update(extra)
    return record


def append_record(path: str, record: dict) -> None:
    """Append one record as a single ``O_APPEND`` write (one line).
    POSIX append semantics keep concurrent writers' lines whole —
    loadgen's N simultaneous builds share one history file safely."""
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    line = json.dumps(record, separators=(",", ":"),
                      default=str) + "\n"
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, line.encode())
    finally:
        os.close(fd)


def read_history(path: str) -> list[dict]:
    """Load history records from a file, or every ``*.jsonl`` under a
    directory, ordered by timestamp. Unknown-schema lines are skipped
    (the set is open, like the event bus); torn final lines of a
    killed build are salvaged like every other JSONL artifact."""
    files: list[str]
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, name) for name in os.listdir(path)
            if name.endswith(".jsonl"))
    else:
        files = [path]
    records: list[dict] = []
    for name in files:
        for line in events.read_jsonl(name, skip_invalid=True):
            if line.get("schema") == HISTORY_SCHEMA:
                records.append(line)
    records.sort(key=lambda r: r.get("ts", 0.0))
    return records


# -- aggregation -----------------------------------------------------------


def aggregate(records: list[dict]) -> dict:
    """The digest ``history diff`` gates on: duration percentiles,
    pooled cache hit ratio, pooled chunk dedup ratio."""
    durations = [float(r.get("duration_seconds", 0.0))
                 for r in records]
    hits = sum(int(r.get("cache", {}).get("hits", 0)) for r in records)
    misses = sum(int(r.get("cache", {}).get("misses", 0))
                 for r in records)
    added = sum(int(r.get("cache", {}).get("chunk_bytes_added", 0))
                for r in records)
    reused = sum(int(r.get("cache", {}).get("chunk_bytes_reused", 0))
                 for r in records)
    out: dict[str, Any] = {
        "records": len(records),
        "failures": sum(1 for r in records
                        if int(r.get("exit_code", 0) or 0) != 0),
        "cache_hit_ratio": round(hits / (hits + misses), 4)
        if hits + misses else 0.0,
        "chunk_dedup_ratio": round(reused / (added + reused), 4)
        if added + reused else 0.0,
    }
    if durations:
        out["duration_p50"] = round(
            metrics.percentile(durations, 50), 6)
        out["duration_p99"] = round(
            metrics.percentile(durations, 99), 6)
        out["duration_max"] = round(max(durations), 6)
    # Dominant device-route label across the set (records without the
    # label — pre-PR-9 files — contribute nothing).
    probes: dict[str, int] = {}
    for r in records:
        label = r.get("device_probe")
        if label:
            probes[label] = probes.get(label, 0) + 1
    if probes:
        out["device_probe"] = max(sorted(probes), key=probes.get)
    # Dominant residency label (records without it — pre-session
    # files — contribute nothing).
    warm: dict[str, int] = {}
    for r in records:
        label = r.get("warm_mode")
        if label and label != "none":
            warm[label] = warm.get(label, 0) + 1
    if warm:
        out["warm_mode"] = max(sorted(warm), key=warm.get)
    # Routing mix: how these builds reached their process — "fleet"
    # (front-door provenance present) vs "direct" — plus the dominant
    # worker among fleet-routed records. A latency swing that rides a
    # routing change (warm affinity landing elsewhere, a failover-heavy
    # run) is topology, not code; `history diff` names it like the
    # device-route and warm-mode labels.
    # Alerts fired during these builds' windows (records carry the
    # per-invocation delta of makisu_alerts_fired_total). Summed, plus
    # a per-record rate — the signal `history diff` uses to say "the
    # candidate ran under an alert storm".
    alert_counts = [int(r.get("alerts_fired", 0) or 0)
                    for r in records if "alerts_fired" in r]
    if alert_counts:
        out["alerts_fired"] = sum(alert_counts)
        out["alert_rate"] = round(
            sum(alert_counts) / len(alert_counts), 4)
    via_fleet = [r for r in records if isinstance(r.get("fleet"), dict)]
    if records:
        out["routing"] = ("fleet" if len(via_fleet) * 2 > len(records)
                          else "direct")
        out["fleet_routed"] = len(via_fleet)
    workers: dict[str, int] = {}
    for r in via_fleet:
        worker = str(r["fleet"].get("worker", ""))
        if worker:
            workers[worker] = workers.get(worker, 0) + 1
    if workers:
        out["dominant_worker"] = max(sorted(workers), key=workers.get)
    # Storage-plane snapshot: the LATEST record carrying one (records
    # gain `storage_bytes` from the cached census — cli.main attaches
    # it when a census.json exists). Latest wins because disk usage is
    # a level, not a rate: the newest record IS the current state.
    for r in reversed(records):
        planes = r.get("storage_bytes")
        if isinstance(planes, dict) and planes:
            out["storage_bytes"] = dict(planes)
            break
    return out


def diff(a: list[dict], b: list[dict],
         threshold: float = 0.25) -> dict:
    """Compare history set ``b`` (candidate) against ``a`` (baseline)
    and flag regressions beyond ``threshold`` (a fraction: 0.25 flags
    a >25% p50 latency growth or a >25% relative hit-ratio drop).
    Ratios with no samples on either side are skipped, not flagged."""
    agg_a, agg_b = aggregate(a), aggregate(b)
    regressions: list[dict] = []
    if not a or not b:
        # No records on one side = no signal, not a regression — an
        # empty candidate file must fail loudly elsewhere (the caller
        # sees records: 0 in the rendered diff), not masquerade as a
        # 100% cache drop.
        return {"baseline": agg_a, "candidate": agg_b,
                "threshold": threshold, "regressions": [],
                "ok": True, "insufficient_records": True}
    for key, direction in _GATES:
        va, vb = agg_a.get(key), agg_b.get(key)
        if va is None or vb is None:
            continue
        if va <= 0:
            # Nothing to regress from (no baseline samples, or a zero
            # ratio): a gate needs a meaningful denominator.
            continue
        change = (vb - va) / va
        flagged = (change > threshold if direction == "up"
                   else change < -threshold)
        if flagged:
            regressions.append({
                "metric": key,
                "baseline": va,
                "candidate": vb,
                "change": round(change, 4),
            })
    result: dict[str, Any] = {
        "baseline": agg_a,
        "candidate": agg_b,
        "threshold": threshold,
        "regressions": regressions,
        "ok": not regressions,
    }
    # Device-route attribution: a p50/p99 swing alongside a route-state
    # change (ok → wedged: chunk hashing degraded to whole-layer
    # caching) is environment, not code — the diff names it so the
    # gate's reader doesn't chase a phantom regression.
    da, db = agg_a.get("device_probe"), agg_b.get("device_probe")
    if da and db and da != db:
        result["device_probe_change"] = {"baseline": da,
                                         "candidate": db}
    # Residency attribution: a latency delta alongside a warm-mode
    # flip (resident → off: every rebuild re-paid the scan/re-chunk
    # floor) is residency state, not code — name it.
    wa, wb = agg_a.get("warm_mode"), agg_b.get("warm_mode")
    if wa and wb and wa != wb:
        result["warm_mode_change"] = {"baseline": wa, "candidate": wb}
    # Routing-mix attribution: direct → fleet (or a dominant-worker
    # flip) changes which machine's warm state and disks served the
    # builds — name it next to the latency gates.
    ra, rb = agg_a.get("routing"), agg_b.get("routing")
    dwa = agg_a.get("dominant_worker")
    dwb = agg_b.get("dominant_worker")
    if (ra and rb and ra != rb) or (dwa and dwb and dwa != dwb):
        result["routing_change"] = {
            "baseline": ra, "candidate": rb,
            **({"baseline_worker": dwa, "candidate_worker": dwb}
               if dwa != dwb and (dwa or dwb) else {}),
        }
    # Alert-rate attribution: a candidate whose builds fired alerts
    # where the baseline's fired none (or at a rate grown beyond the
    # threshold) ran DEGRADED — SLO breaches during the measurement
    # window explain latency swings the perf gates would otherwise
    # pin on the code change. Named like the device-route/warm-mode/
    # routing attributions; skipped when neither side carries the
    # label (pre-SLO files).
    aa = agg_a.get("alert_rate")
    ab = agg_b.get("alert_rate")
    if aa is not None or ab is not None:
        aa_v = float(aa or 0.0)
        ab_v = float(ab or 0.0)
        grew = (ab_v > 0.0 and aa_v == 0.0) or (
            aa_v > 0.0 and (ab_v - aa_v) / aa_v > threshold)
        if grew:
            result["alert_rate_change"] = {
                "baseline": aa_v, "candidate": ab_v,
                "baseline_fired": int(agg_a.get("alerts_fired", 0)),
                "candidate_fired": int(agg_b.get("alerts_fired", 0)),
            }
    # Storage-growth gate: a content plane that grew beyond the
    # threshold between baseline and candidate is a retention leak the
    # perf gates can't see (the build got no slower — the disk just
    # filled). Skipped when either side lacks the snapshot (pre-PR-16
    # files), like every other optional label.
    sa = agg_a.get("storage_bytes") or {}
    sb = agg_b.get("storage_bytes") or {}
    growth: list[dict] = []
    for plane in sorted(set(sa) | set(sb)):
        if plane == "total":
            continue
        va = int(sa.get(plane, 0) or 0)
        vb = int(sb.get(plane, 0) or 0)
        if va <= 0:
            continue
        change = (vb - va) / va
        if change > threshold:
            growth.append({"plane": plane, "baseline": va,
                           "candidate": vb,
                           "change": round(change, 4)})
    if growth:
        result["storage_growth"] = growth
        result["ok"] = False
    return result


# -- renderers -------------------------------------------------------------


def _fmt_phases(phases: dict) -> str:
    return " ".join(f"{name}={seconds:.2f}s"
                    for name, seconds in sorted(
                        phases.items(), key=lambda kv: -kv[1])[:3])


def render_trends(records: list[dict], limit: int = 20) -> str:
    """The ``makisu-tpu history PATH`` output: aggregate digest plus
    the most recent ``limit`` records, oldest first."""
    lines = [f"build history — {len(records)} records"]
    if not records:
        return lines[0] + "\n"
    agg = aggregate(records)
    lines.append(
        f"duration p50 {agg.get('duration_p50', 0.0):.3f}s  "
        f"p99 {agg.get('duration_p99', 0.0):.3f}s  "
        f"max {agg.get('duration_max', 0.0):.3f}s")
    lines.append(
        f"cache hit ratio {100.0 * agg['cache_hit_ratio']:.1f}%  "
        f"chunk dedup {100.0 * agg['chunk_dedup_ratio']:.1f}%  "
        f"failures {agg['failures']}/{agg['records']}"
        + (f"  device route {agg['device_probe']}"
           if agg.get("device_probe") else "")
        + (f"  warm mode {agg['warm_mode']}"
           if agg.get("warm_mode") else ""))
    lines.append("")
    shown = records[-limit:]
    if len(records) > limit:
        lines.append(f"(showing last {limit} of {len(records)})")
    for r in shown:
        ts = time.strftime("%Y-%m-%d %H:%M:%S",
                           time.localtime(r.get("ts", 0.0)))
        cache = r.get("cache", {})
        lookups = cache.get("hits", 0) + cache.get("misses", 0)
        cache_part = (f"cache {100.0 * cache.get('hit_ratio', 0.0):.0f}%"
                      if lookups else "cache -")
        code = int(r.get("exit_code", 0) or 0)
        lines.append(
            f"  {ts}  {r.get('command', '?'):<6s}"
            f" {r.get('duration_seconds', 0.0):8.3f}s"
            f"  {cache_part:<10s}"
            f" {'ok' if code == 0 else f'exit {code}'}"
            + (f"  [{_fmt_phases(r['phase_self_seconds'])}]"
               if r.get("phase_self_seconds") else ""))
    return "\n".join(lines) + "\n"


def render_diff(result: dict) -> str:
    """The ``makisu-tpu history diff A B`` output."""
    agg_a, agg_b = result["baseline"], result["candidate"]
    lines = [
        "build history diff — baseline vs candidate "
        f"(threshold {100.0 * result['threshold']:.0f}%)",
        f"  records: {agg_a['records']} vs {agg_b['records']}",
    ]
    for key, _direction in _GATES:
        va, vb = agg_a.get(key), agg_b.get(key)
        if va is None or vb is None:
            continue
        flagged = any(r["metric"] == key
                      for r in result["regressions"])
        delta = ""
        if va:
            delta = f"  ({100.0 * (vb - va) / va:+.1f}%)"
        lines.append(f"  {key:<18s} {va:10.4f} → {vb:10.4f}{delta}"
                     + ("  ← REGRESSION" if flagged else ""))
    change = result.get("device_probe_change")
    if change:
        lines.append(
            f"  device route: {change['baseline']} → "
            f"{change['candidate']}  (latency deltas may be "
            f"device-route state, not code)")
    warm_change = result.get("warm_mode_change")
    if warm_change:
        lines.append(
            f"  warm mode: {warm_change['baseline']} → "
            f"{warm_change['candidate']}  (latency deltas may be "
            f"residency state, not code)")
    routing_change = result.get("routing_change")
    if routing_change:
        detail = f"{routing_change['baseline']} → " \
                 f"{routing_change['candidate']}"
        if routing_change.get("baseline_worker") \
                or routing_change.get("candidate_worker"):
            detail += (f" (worker "
                       f"{routing_change.get('baseline_worker') or '-'}"
                       f" → "
                       f"{routing_change.get('candidate_worker') or '-'})")
        lines.append(
            f"  routing mix: {detail}  (latency deltas may be fleet "
            f"placement, not code)")
    alert_change = result.get("alert_rate_change")
    if alert_change:
        lines.append(
            f"  alert rate: {alert_change['baseline']:g} → "
            f"{alert_change['candidate']:g} fired/build "
            f"({alert_change['baseline_fired']} → "
            f"{alert_change['candidate_fired']} total)  (candidate "
            f"ran under SLO alerts — latency deltas may be a degraded "
            f"fleet, not code)")
    growth = result.get("storage_growth") or []
    for g in growth:
        lines.append(
            f"  storage plane {g['plane']}: {g['baseline']} → "
            f"{g['candidate']} bytes "
            f"({100.0 * g['change']:+.1f}%)  ← GROWTH")
    lines.append("")
    if result["regressions"] or growth:
        names = ", ".join(
            [r["metric"] for r in result["regressions"]]
            + [f"storage:{g['plane']}" for g in growth])
        lines.append(f"REGRESSION: {names} beyond the "
                     f"{100.0 * result['threshold']:.0f}% threshold")
        if any(r["metric"].startswith("duration")
               for r in result["regressions"]):
            lines.append(
                "  attribute it to frames: capture profiles of both "
                "builds (--profile-out) and run `makisu-tpu profile "
                "diff BASELINE CANDIDATE`")
    else:
        lines.append("ok: no regression beyond the threshold")
    return "\n".join(lines) + "\n"
