"""Alert lifecycle: firing/resolved state with hysteresis, and sinks.

The SLO plane (``fleet/slo.py``) decides *whether* a rule is breached;
this module owns what happens next — the state machine between
``firing`` and ``resolved``, and every surface an alert transition
must reach:

- the **event bus**: transitions ride as ``alert`` events (so they
  land in flight-recorder bundles and ``--events-out`` files for
  free, like every other structured record in the repo);
- **metrics**: ``makisu_alerts_fired_total`` / ``_resolved_total``
  counters and the ``makisu_alert_active{rule,severity}`` gauge a
  threshold rule or dashboard reads directly;
- the **active-alert ring** served at ``GET /alerts`` on worker and
  fleet servers (bounded: active alerts plus a recently-resolved
  ring, so a flapping rule can't grow the payload without bound);
- an optional **webhook**: each transition POSTed as JSON to an
  operator-supplied HTTP endpoint (``--alert-webhook``), bounded
  timeout, outcome counted — a dead receiver costs a counter bump,
  never an evaluation tick.

Flap suppression lives here as *resolve hysteresis*: a firing alert
resolves only after ``resolve_after`` consecutive clear evaluations.
(The symmetric fire-side hysteresis — ``breach_for`` consecutive
breached ticks — belongs to the rule, so it lives in the evaluator.)

Like the rest of the telemetry layer: stdlib-only, import-cycle-free,
and never able to fail the thread that calls it.
"""

from __future__ import annotations

import collections
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any

from makisu_tpu.utils import events, metrics

ALERT_EVENT_TYPE = "alert"
ALERT_SCHEMA = "makisu-tpu.alert.v1"

# Severity vocabulary, worst-first — shared by the rule defs, the
# /alerts payload ordering, doctor's finding mapping, and the CLI
# render. Unknown severities sort last (the set is open the same way
# event types are).
SEVERITY_RANK = {"page": 0, "warn": 1, "info": 2}

# Recently-resolved ring size on the /alerts payload.
_RECENT_KEEP = 64

# Webhook delivery budget. A transition is worth one bounded POST; a
# slow receiver must not stall the evaluation loop behind it.
_WEBHOOK_TIMEOUT = 3.0


def severity_rank(severity: str) -> int:
    return SEVERITY_RANK.get(str(severity), len(SEVERITY_RANK))


def sort_alerts(alerts: list[dict]) -> list[dict]:
    """Severity-major ordering (page first), newest fire first within
    a severity — the order every render surface uses."""
    return sorted(alerts, key=lambda a: (
        severity_rank(a.get("severity", "")),
        -float(a.get("fired_ts", 0.0)),
        str(a.get("rule", "")), str(a.get("label", ""))))


class _AlertState:
    """One (rule, label) pair's lifecycle state."""

    __slots__ = ("rule", "label", "severity", "firing", "value",
                 "threshold", "message", "fired_ts", "resolved_ts",
                 "clear_streak", "fire_count")

    def __init__(self, rule: str, label: str, severity: str) -> None:
        self.rule = rule
        self.label = label
        self.severity = severity
        self.firing = False
        self.value: float | None = None
        self.threshold: float | None = None
        self.message = ""
        self.fired_ts = 0.0
        self.resolved_ts = 0.0
        self.clear_streak = 0
        self.fire_count = 0

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "rule": self.rule,
            "severity": self.severity,
            "state": "firing" if self.firing else "resolved",
            "message": self.message,
            "fired_ts": round(self.fired_ts, 3),
            "fire_count": self.fire_count,
        }
        if self.label:
            out["label"] = self.label
        if self.value is not None:
            out["value"] = round(float(self.value), 6)
        if self.threshold is not None:
            out["threshold"] = round(float(self.threshold), 6)
        if not self.firing and self.resolved_ts:
            out["resolved_ts"] = round(self.resolved_ts, 3)
            out["active_seconds"] = round(
                self.resolved_ts - self.fired_ts, 3)
        return out


class AlertManager:
    """Per-(rule, label) alert state machine plus every sink.

    ``observe`` is the single entry point: the evaluator calls it once
    per rule (per label) per tick with the breach verdict. Transitions
    return ``"fired"`` / ``"resolved"`` (steady states return
    ``None``) so callers — and tests — see exactly when the machine
    moved. Thread-safe; sink fan-out happens outside the lock."""

    def __init__(self, resolve_after: int = 2, webhook: str = "",
                 source: str = "") -> None:
        # resolve_after < 1 would resolve on the first clear tick with
        # no suppression at all; clamp to the minimum meaningful value.
        self.resolve_after = max(1, int(resolve_after))
        self.webhook = webhook
        self.source = source  # "worker"/"fleet": stamped on events
        self._mu = threading.Lock()
        self._states: dict[tuple[str, str], _AlertState] = {}
        self._recent: collections.deque[dict] = collections.deque(
            maxlen=_RECENT_KEEP)
        # Optional fired-hook for page-severity transitions: the
        # worker/fleet front door attach a profile-tail dump here, so
        # the moment a page fires there is a "where was the time going"
        # artifact next to the alert. Called outside the lock;
        # exceptions are swallowed (forensics never wedges the
        # evaluator).
        self.on_fire = None

    # -- state machine ----------------------------------------------------

    def observe(self, rule: str, breached: bool, *,
                severity: str = "warn", label: str = "",
                value: float | None = None,
                threshold: float | None = None,
                message: str = "") -> str | None:
        """Feed one evaluation of one rule (one label). Fire is
        immediate on ``breached`` (the evaluator already applied any
        ``breach_for`` fire-side hysteresis); resolve waits for
        ``resolve_after`` consecutive clear observations."""
        transition: str | None = None
        with self._mu:
            key = (rule, label)
            state = self._states.get(key)
            if state is None:
                if not breached:
                    return None  # never fired; nothing to track
                state = self._states[key] = _AlertState(
                    rule, label, severity)
            state.severity = severity
            if value is not None:
                state.value = value
            if threshold is not None:
                state.threshold = threshold
            if message:
                state.message = message
            if breached:
                state.clear_streak = 0
                if not state.firing:
                    state.firing = True
                    state.fired_ts = time.time()
                    state.resolved_ts = 0.0
                    state.fire_count += 1
                    transition = "fired"
            elif state.firing:
                state.clear_streak += 1
                if state.clear_streak >= self.resolve_after:
                    state.firing = False
                    state.resolved_ts = time.time()
                    state.clear_streak = 0
                    transition = "resolved"
                    self._recent.append(state.to_dict())
            payload = state.to_dict() if transition else None
        if transition:
            self._publish(transition, payload)
        return transition

    # -- reads ------------------------------------------------------------

    def active(self) -> list[dict]:
        with self._mu:
            rows = [s.to_dict() for s in self._states.values()
                    if s.firing]
        return sort_alerts(rows)

    def recent(self) -> list[dict]:
        with self._mu:
            return list(reversed(self._recent))

    def snapshot(self) -> dict[str, Any]:
        """The ``GET /alerts`` payload body."""
        active = self.active()
        counts: dict[str, int] = {}
        for a in active:
            sev = str(a.get("severity", ""))
            counts[sev] = counts.get(sev, 0) + 1
        return {
            "schema": ALERT_SCHEMA,
            "active": active,
            "recent": self.recent(),
            "counts": {"active": len(active), **counts},
        }

    def digest(self) -> dict[str, int]:
        """Cheap active-count summary for /healthz (polled every few
        seconds — must not serialize full alert rows)."""
        with self._mu:
            active = [s for s in self._states.values() if s.firing]
            return {
                "active": len(active),
                "page": sum(1 for s in active if s.severity == "page"),
                "warn": sum(1 for s in active if s.severity == "warn"),
            }

    # -- sinks ------------------------------------------------------------

    def _publish(self, transition: str, payload: dict) -> None:
        fields = dict(payload)
        if self.source:
            fields.setdefault("source", self.source)
        events.emit(ALERT_EVENT_TYPE, **fields)
        rule = payload.get("rule", "?")
        severity = payload.get("severity", "?")
        g = metrics.global_registry()
        if transition == "fired":
            g.counter_add(metrics.ALERTS_FIRED,
                          rule=rule, severity=severity)
            g.gauge_set(metrics.ALERT_ACTIVE, 1,
                        rule=rule, severity=severity)
        else:
            g.counter_add(metrics.ALERTS_RESOLVED,
                          rule=rule, severity=severity)
            g.gauge_set(metrics.ALERT_ACTIVE, 0,
                        rule=rule, severity=severity)
        if self.webhook:
            self._post_webhook(transition, payload)
        if (transition == "fired" and severity == "page"
                and self.on_fire is not None):
            try:
                self.on_fire(payload)
            except Exception:  # noqa: BLE001 - hook never wedges alerts
                events.emit("alert_hook_error",
                            rule=payload.get("rule", "?"))

    def _post_webhook(self, transition: str, payload: dict) -> None:
        """One bounded POST per transition. Failures are counted, not
        raised — a dead receiver must never wedge the evaluator."""
        body = json.dumps({
            "schema": ALERT_SCHEMA,
            "transition": transition,
            "source": self.source,
            "alert": payload,
        }, default=str).encode()
        req = urllib.request.Request(
            self.webhook, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        g = metrics.global_registry()
        try:
            with urllib.request.urlopen(
                    req, timeout=_WEBHOOK_TIMEOUT) as resp:
                result = "ok" if 200 <= resp.status < 300 else "error"
        except (urllib.error.URLError, OSError, ValueError):
            result = "error"
        g.counter_add(metrics.ALERT_WEBHOOK, result=result)


def render_alerts(snapshot: dict, heading: str = "") -> str:
    """Human render of one /alerts payload — the ``makisu-tpu alerts``
    subcommand's output, also reused by doctor. Pure function of the
    payload, so tests feed canned snapshots."""
    lines: list[str] = []
    if heading:
        lines.append(heading)
    active = sort_alerts(list(snapshot.get("active") or []))
    if not active:
        lines.append("no active alerts")
    else:
        lines.append(f"{len(active)} active alert"
                     f"{'s' if len(active) != 1 else ''}:")
        for a in active:
            name = a.get("rule", "?")
            if a.get("label"):
                name = f"{name}[{a['label']}]"
            age = time.time() - float(a.get("fired_ts", time.time()))
            detail = a.get("message", "")
            value = a.get("value")
            threshold = a.get("threshold")
            if value is not None and threshold is not None:
                detail += (f" (value {value:g} vs threshold "
                           f"{threshold:g})")
            lines.append(f"  [{a.get('severity', '?'):4s}] {name}: "
                         f"{detail} — firing {age:.0f}s")
    recent = list(snapshot.get("recent") or [])
    if recent:
        lines.append(f"recently resolved ({len(recent)}):")
        for a in recent[:8]:
            name = a.get("rule", "?")
            if a.get("label"):
                name = f"{name}[{a['label']}]"
            lines.append(
                f"  [{a.get('severity', '?'):4s}] {name}: resolved "
                f"after {a.get('active_seconds', 0):g}s")
    return "\n".join(lines)
