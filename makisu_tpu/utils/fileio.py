"""Recursive file copying with ownership policies.

Reference capability: lib/fileio/copy.go (Copier, WithDstDirOwner:98,
WithDstFileAndChildrenOwner:108). Behavior preserved: blacklist pruning,
symlinks copied as links (never chowned), special files skipped, existing
destinations overwritten, dst dirs created 0755/root by default, ownership
override policies for COPY --chown / context copies / --archive.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import threading

from makisu_tpu.utils import pathutils, sysutils


def write_json_atomic(path: str, payload, default=str) -> None:
    """Crash-safe JSON write: serialize to a uniquely-named temp file
    in the destination directory, fsync it, then rename over ``path``.
    A reader (or the next build) sees either the old complete file or
    the new complete file — never a truncation, even across a SIGTERM
    mid-write or a power cut after the rename (the fsync orders the
    data before the metadata). The temp name carries pid AND thread id:
    concurrent builds in one worker process must not clobber each
    other's in-flight writes."""
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, separators=(",", ":"),
                      default=default)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # Unwinding through here includes a signal handler's
        # SystemExit — the orphan temp file must not accumulate.
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_bytes_atomic(path: str, payload: bytes) -> None:
    """Crash-safe byte-blob write, same discipline as
    :func:`write_json_atomic` (unique temp name, fsync, rename) — used
    for artifacts a reader must never see torn (seekable-pack frame
    files, whose offsets an index references)."""
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


@dataclasses.dataclass(frozen=True)
class Owner:
    uid: int
    gid: int
    overwrite: bool  # force this owner instead of the source's


def _copy_times(src: str, dst: str) -> None:
    st = os.lstat(src)
    os.utime(dst, ns=(st.st_atime_ns, st.st_mtime_ns))


def _chown(path: str, uid: int, gid: int, follow_symlinks=True) -> None:
    try:
        os.chown(path, uid, gid, follow_symlinks=follow_symlinks)
    except PermissionError:
        pass  # unprivileged builds keep current ownership


class Copier:
    """Copies files/trees, applying destination ownership policies.

    ``dir_owner`` applies to the destination directory itself (and any
    directories created along the way when ``overwrite``); ``file_owner``
    applies to copied files and, when ``overwrite``, to every child.
    """

    def __init__(self, blacklist: list[str] | None = None,
                 dir_owner: Owner | None = None,
                 file_owner: Owner | None = None) -> None:
        self.blacklist = list(blacklist or [])
        self.dir_owner = dir_owner
        self.file_owner = file_owner
        # Ancestor dirs this copier synthesized (no source counterpart,
        # so no mtime to preserve). Callers producing layers timestamp
        # them deterministically afterwards (CopyOperation.execute) so
        # the disk state matches the epoch-mtime headers MemFS
        # synthesizes for the same paths — otherwise the next scan diff
        # re-emits every such dir with the wall clock in it.
        self.created_dirs: list[str] = []

    def _blacklisted(self, p: str) -> bool:
        return pathutils.is_descendant_of_any(p, self.blacklist)

    def copy_file(self, src: str, dst: str) -> None:
        self._mkdir_ancestors(os.path.dirname(dst))
        self._copy_file(src, dst)

    def copy_dir(self, src: str, dst: str) -> None:
        if self._blacklisted(src):
            return
        self._mkdir_ancestors(os.path.dirname(dst))
        self._ensure_dir(src, dst, top=True)
        self._copy_dir_contents(src, dst, dst)
        _copy_times(src, dst)

    # -- internals --------------------------------------------------------

    def _mkdir_ancestors(self, dst: str) -> None:
        """Create missing ancestor dirs with default mode 0755, root-owned."""
        dst = os.path.abspath(dst)
        parts = pathutils.split_path(dst)
        cur = "/"
        for part in parts:
            cur = os.path.join(cur, part)
            if not os.path.lexists(cur):
                os.mkdir(cur, 0o755)
                _chown(cur, 0, 0)
                self.created_dirs.append(cur)

    def _ensure_dir(self, src: str, dst: str, top: bool) -> None:
        """Create/update one destination directory from a source directory."""
        st = os.lstat(src)
        if not os.path.lexists(dst):
            os.mkdir(dst, st.st_mode & 0o7777)
        elif not os.path.isdir(dst):
            raise NotADirectoryError(f"dst {dst} is not a directory")
        uid, gid = st.st_uid, st.st_gid
        owner = self.dir_owner if top else None
        if owner is None and self.file_owner and self.file_owner.overwrite:
            owner = self.file_owner
        if owner is not None:
            uid, gid = owner.uid, owner.gid
        _chown(dst, uid, gid)
        os.chmod(dst, st.st_mode & 0o7777)

    def _copy_dir_contents(self, src: str, dst: str, orig_dst: str) -> None:
        for name in sorted(os.listdir(src)):
            cur_src = os.path.join(src, name)
            if self._blacklisted(cur_src) or cur_src == orig_dst:
                continue  # orig_dst check breaks dst-inside-src loops
            cur_dst = os.path.join(dst, name)
            if os.path.isdir(cur_src) and not os.path.islink(cur_src):
                self._ensure_dir(cur_src, cur_dst, top=False)
                self._copy_dir_contents(cur_src, cur_dst, orig_dst)
                # Post-order so child writes don't clobber the dir mtime.
                _copy_times(cur_src, cur_dst)
            else:
                self._copy_file(cur_src, cur_dst)

    def _copy_file(self, src: str, dst: str) -> None:
        if self._blacklisted(src):
            return
        st = os.lstat(src)
        if os.path.islink(src):
            if os.path.lexists(dst):
                os.remove(dst)
            os.symlink(os.readlink(src), dst)
            return  # symlinks are never chowned/chmodded
        if sysutils.is_special_file(st):
            return
        if os.path.lexists(dst) and not os.path.isdir(dst):
            os.chmod(dst, 0o777)
        with open(src, "rb") as r, open(dst, "wb") as w:
            shutil.copyfileobj(r, w)
        uid, gid = st.st_uid, st.st_gid
        if self.file_owner and self.file_owner.overwrite:
            uid, gid = self.file_owner.uid, self.file_owner.gid
        _chown(dst, uid, gid)
        os.chmod(dst, st.st_mode & 0o7777)
        # Preserve mtime: the snapshot layer records the source's header,
        # so the on-disk copy must look identical or the next scan-diff
        # re-adds every copied file.
        os.utime(dst, ns=(st.st_atime_ns, st.st_mtime_ns))


def reader_to_file(reader, dst: str) -> int:
    """Stream a file-like reader to dst (reference: fileio.ReaderToFile:35)."""
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    n = 0
    with open(dst, "wb") as f:
        while True:
            chunk = reader.read(1 << 20)
            if not chunk:
                return n
            f.write(chunk)
            n += len(chunk)
