"""Shared utilities: paths, logging, io, mounts, http."""
