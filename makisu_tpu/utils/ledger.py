"""Cache-decision ledger: every cache consult, attributed and durable.

Metrics (PR 1) count cache outcomes and events (PR 2) order them, but
neither can answer the question that gates the incremental-rebuild work:
*why* did this layer rebuild — which Dockerfile node broke the cache
chain, which files' changed bytes broke it, and how many bytes the
chunk plane actually had to re-move. This module is that record.

Every cache consult — the stat-cache probe behind a COPY/ADD cache ID,
the KV ``pull_cache`` entry lookup, the chunk-CAS existence scan, the
chunk-index dedup pass after a commit — records one structured
**decision** through the existing event bus as a ``cache_decision``
event:

```jsonc
{"ts": ..., "type": "cache_decision",
 "source": "kv" | "statcache" | "chunk_cas" | "chunk_index",
 "key": "<cache id / layer hex>",
 "verdict": "hit" | "miss" | "stale" | "error" | "empty" | "partial"
          | "indexed",
 "reason": "absent" | "kv_error" | "decode_error" | "layer_not_local"
         | "blob_gone" | "gz_backend" | "chunks_incomplete" | ...,
 // attribution (when a build node is in scope):
 "stage": "0", "step": 2, "directive": "COPY",
 // economics (source-specific):
 "bytes_saved": ..., "bytes_refetched": ..., "bytes_added": ...}
```

Because decisions ARE events, they reach every existing consumer for
free: ``--events-out``, the worker's live ``/build`` NDJSON frames, and
the flight recorder's ring. ``--explain-out FILE`` additionally writes
the compact per-build **ledger artifact**: a JSONL file holding a
header line (schema ``makisu-tpu.ledger.v1``), one line per decision,
and a trailing summary line with the aggregates (hit/miss counts by
source, bytes saved vs refetched, chunk dedup ratio, stat-cache blame).
``makisu-tpu explain`` renders miss attribution, build-to-build diffs,
and the warm-rebuild floor profile from these files
(``utils/explain.py``).

Like the rest of the telemetry layer: stdlib-only, context-scoped via
the event bus, free when no sink is bound, and never able to fail a
build.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import threading
from typing import Any, Iterator

from makisu_tpu.utils import events

LEDGER_SCHEMA = "makisu-tpu.ledger.v1"

# The ledger's event type on the bus (consumers that predate it skip
# unknown types by contract).
EVENT_TYPE = "cache_decision"

# Coarse miss-reason buckets for makisu_cache_miss_total{reason=...}.
# The ledger keeps the precise sub-reason; the counter keeps stable,
# low-cardinality series an alert can be written against.
COARSE_REASONS = {
    "absent": "absent",
    "kv_error": "kv_error",
    "decode_error": "decode_error",
    "layer_not_local": "stale",
    "blob_gone": "stale",
    "gz_backend": "stale",
    "chunks_incomplete": "stale",
}


def coarse_reason(reason: str | None) -> str:
    return COARSE_REASONS.get(reason or "", "absent")


# -- build-node attribution -------------------------------------------------

# Which Dockerfile node the current code is working FOR. Context-scoped
# like the metrics registry: threads a node's work spawns (async cache
# pushes, chunk uploads) inherit it via contextvars.copy_context, so a
# chunk-index decision landing seconds after the step finished still
# names the right node.
_node: "contextvars.ContextVar[dict | None]" = contextvars.ContextVar(
    "makisu_ledger_node", default=None)


@contextlib.contextmanager
def node_scope(**fields: Any) -> Iterator[None]:
    """Attribute every decision recorded inside to this build node
    (``stage=<alias>, step=<index>, directive=<COPY|RUN|...>``)."""
    token = _node.set({k: v for k, v in fields.items() if v is not None})
    try:
        yield
    finally:
        _node.reset(token)


def current_node() -> dict | None:
    return _node.get()


def record(source: str, key: str, verdict: str,
           reason: str | None = None, **fields: Any) -> None:
    """Record one cache decision. Free no-op when no event sink is
    bound (same contract as ``events.emit``); never raises."""
    if not events.active():
        return
    payload: dict[str, Any] = {"source": source, "key": key,
                               "verdict": verdict}
    if reason:
        payload["reason"] = reason
    node = _node.get()
    if node:
        payload.update(node)
    payload.update(fields)
    events.emit(EVENT_TYPE, **payload)


# -- summary accumulation ---------------------------------------------------

# Cap on file paths carried in the summary's blame list: the ledger is
# a compact artifact; a 100k-file edit names the first N and counts the
# rest.
BLAME_FILES_KEEP = 50


class LedgerSummary:
    """Aggregates decisions into the trailing summary line. Shared by
    the writer (accumulating live) and the reader (recomputing when a
    torn ledger lost its summary line)."""

    def __init__(self) -> None:
        self.decisions = 0
        self.verdicts: dict[str, int] = {}
        self.by_source: dict[str, dict[str, int]] = {}
        self.bytes_saved = 0        # layer bytes served from cache
        self.bytes_refetched = 0    # chunk bytes moved over the wire
        self.bytes_added = 0        # novel chunk bytes (re-chunked)
        self.bytes_reused = 0       # chunk bytes dedup found locally
        self.chunks_indexed = 0
        self.chunks_reused = 0
        self.stat_hits = 0
        self.stat_misses = 0
        self.changed_files: list[str] = []
        self.exit_code: int | None = None

    def add(self, decision: dict) -> None:
        self.decisions += 1
        verdict = str(decision.get("verdict", "?"))
        source = str(decision.get("source", "?"))
        self.verdicts[verdict] = self.verdicts.get(verdict, 0) + 1
        per = self.by_source.setdefault(source, {})
        per[verdict] = per.get(verdict, 0) + 1
        self.bytes_saved += int(decision.get("bytes_saved", 0) or 0)
        self.bytes_refetched += int(
            decision.get("bytes_refetched", 0) or 0)
        if source == "chunk_index":
            self.bytes_added += int(decision.get("bytes_added", 0) or 0)
            self.bytes_reused += int(
                decision.get("bytes_reused", 0) or 0)
            self.chunks_indexed += int(decision.get("added", 0) or 0)
            self.chunks_reused += int(
                int(decision.get("chunks", 0) or 0)
                - int(decision.get("added", 0) or 0))
        if source == "statcache":
            self.stat_hits += int(decision.get("hits", 0) or 0)
            self.stat_misses += int(decision.get("misses", 0) or 0)
            for rel in decision.get("changed_files", []) or []:
                if (len(self.changed_files) < BLAME_FILES_KEEP
                        and rel not in self.changed_files):
                    self.changed_files.append(rel)

    def dedup_ratio(self) -> float:
        total = self.bytes_added + self.bytes_reused
        return self.bytes_reused / total if total else 0.0

    def to_dict(self) -> dict[str, Any]:
        return {
            "type": "summary",
            "decisions": self.decisions,
            "verdicts": dict(sorted(self.verdicts.items())),
            "by_source": {s: dict(sorted(v.items()))
                          for s, v in sorted(self.by_source.items())},
            "bytes_saved": self.bytes_saved,
            "bytes_refetched": self.bytes_refetched,
            "bytes_added": self.bytes_added,
            "bytes_reused": self.bytes_reused,
            "chunks_indexed": self.chunks_indexed,
            "chunks_reused": self.chunks_reused,
            "dedup_ratio": round(self.dedup_ratio(), 4),
            "statcache": {
                "hits": self.stat_hits,
                "misses": self.stat_misses,
                "changed_files": list(self.changed_files),
            },
            **({"exit_code": self.exit_code}
               if self.exit_code is not None else {}),
        }


class LedgerWriter:
    """Event sink writing the ``--explain-out`` ledger artifact.

    Filters the bus down to ``cache_decision`` events (one JSONL line
    each), bracketed by a header line (schema, trace id, command) on
    open and a summary line on :meth:`close`. Write discipline matches
    ``events.JsonlWriter``: line-at-a-time under a lock, flushed, so a
    killed build tears at most the final line."""

    def __init__(self, path: str, trace_id: str = "",
                 command: str = "") -> None:
        self.path = path
        self.summary = LedgerSummary()
        self._lock = threading.Lock()
        self._closed = False
        self._f = open(path, "w", encoding="utf-8")
        self._write({"schema": LEDGER_SCHEMA, "trace_id": trace_id,
                     "command": command})

    def _write(self, payload: dict) -> None:
        line = json.dumps(payload, separators=(",", ":"), default=str)
        self._f.write(line + "\n")
        self._f.flush()

    def __call__(self, event: dict) -> None:
        etype = event.get("type")
        with self._lock:
            if self._closed:
                return
            if etype == EVENT_TYPE:
                self.summary.add(event)
                self._write(event)
            elif etype == "build_end":
                # Captured for the summary only (cli.main emits it
                # before closing the writer); not a ledger line.
                self.summary.exit_code = event.get("exit_code")

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._write(self.summary.to_dict())
            finally:
                self._f.close()


def read_ledger(path: str, skip_invalid: bool = False) -> dict:
    """Load a ledger (or any ``--events-out`` log containing
    ``cache_decision`` events) into ``{"header": ..., "decisions":
    [...], "summary": ...}``. A ledger torn before its summary line
    (build killed mid-write) gets the summary recomputed from the
    decisions that survived — same salvage contract as
    ``events.read_jsonl(skip_invalid=True)``."""
    lines = events.read_jsonl(path, skip_invalid=skip_invalid)
    header: dict = {}
    summary: dict | None = None
    decisions: list[dict] = []
    for line in lines:
        if line.get("schema") == LEDGER_SCHEMA:
            header = line
        elif line.get("type") == "summary":
            summary = line
        elif line.get("type") == EVENT_TYPE:
            decisions.append(line)
        elif line.get("type") == "build_start" and not header:
            # An --events-out log doubles as ledger input: its
            # build_start line carries the same identity fields.
            header = {"schema": LEDGER_SCHEMA,
                      "trace_id": line.get("trace_id", ""),
                      "command": line.get("command", "")}
    if summary is None:
        acc = LedgerSummary()
        for decision in decisions:
            acc.add(decision)
        summary = acc.to_dict()
        summary["recomputed"] = True
    return {"header": header, "decisions": decisions,
            "summary": summary}
