"""Build telemetry: counters, gauges, histograms, and a span tracer.

Two scopes, mirroring the per-build ``_build_sink`` pattern in
``utils/logging.py``:

- A process-global registry that aggregates everything the process has
  done (what the worker's ``GET /metrics`` Prometheus endpoint serves —
  a scraper wants process totals, not one request's).
- An optional contextvar-bound per-build registry: every counter/gauge/
  histogram write lands in BOTH, and spans attach to the innermost
  bound registry. Threads a build spawns (shell drains, async cache
  pushes, chunk uploads) carry the context along via
  ``contextvars.copy_context``, so concurrent worker builds never mix
  telemetry — the same isolation guarantee the log sinks give.

The span tree is the per-build wall-clock breakdown (``--metrics-out``
writes it as JSON); counters answer rate questions (cache hit ratio,
bytes hashed per backend, registry transfer volume).

Everything here is stdlib-only and import-cycle-free, so any module in
the tree can instrument itself. Telemetry must never fail a build:
writes are cheap dict updates under a lock, and the public helpers
swallow nothing — they simply cannot raise on well-formed names.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from typing import Any, Iterator

from makisu_tpu.utils import events

_LabelKey = tuple[tuple[str, str], ...]

# Histogram buckets default to a duration ladder (seconds); metrics
# with a different shape (batch sizes, fill counts) pass their own on
# first observation.
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   30.0, 60.0)

# The layer-commit pipeline's per-stage telemetry (read_ahead, gear_scan,
# chunk_sha, compress, tar_write). One name pair shared by every stage —
# and by the `makisu-tpu report` bottleneck section — so the series can
# never drift apart.
COMMIT_STAGE_BUSY = "makisu_commit_stage_busy_seconds"
COMMIT_QUEUE_DEPTH = "makisu_commit_queue_depth"

# The compress stage's label under the stage pair above, plus the
# block-parallel stage's own series (tario.BlockGzipWriter and the
# LayerSink compression worker share the label; the block/byte counters
# label backend=zlib|pgzip so the bench compress_micro section and the
# report can split the two formats).
COMPRESS_STAGE = "compress"
COMPRESS_BLOCKS = "makisu_compress_blocks_total"
COMPRESS_BYTES = "makisu_compress_bytes_total"

# Device execution telemetry (ops/backend.py note_device_dispatch):
# one name set shared by the HashService, the chunker's lane batcher,
# the /healthz device section, and the docs' metric table — per lane
# bucket: program round-trip latency, first-dispatch (compile) cost,
# bytes shipped host→device, and padded−real waste inside filled lanes.
DEVICE_DISPATCH_SECONDS = "makisu_device_dispatch_seconds"
DEVICE_COMPILE_SECONDS = "makisu_device_compile_seconds"
DEVICE_H2D_BYTES = "makisu_device_h2d_bytes_total"
DEVICE_PADDING_WASTE = "makisu_device_padding_waste_bytes_total"

# Fleet telemetry (makisu_tpu/fleet/): one name set shared by the
# scheduler, the peer-exchange module, the worker's /chunks endpoint,
# loadgen's fleet report, and the docs' metric table. Routing verdicts
# label makisu_fleet_route_total (affinity|spillover|failover|
# quota_denied); the peer counters count CHUNKS served worker-to-worker
# before any registry round trip.
FLEET_ROUTE_TOTAL = "makisu_fleet_route_total"
FLEET_WORKERS = "makisu_fleet_workers"
FLEET_FRONTDOOR_QUEUE = "makisu_fleet_frontdoor_queue_depth"
FLEET_INFLIGHT_BUILDS = "makisu_fleet_inflight_builds"
FLEET_TENANT_INFLIGHT = "makisu_fleet_tenant_inflight"
FLEET_QUOTA_WAIT = "makisu_fleet_quota_wait_seconds"
FLEET_RETRIES = "makisu_fleet_build_retries_total"
FLEET_BUILD_LATENCY = "makisu_fleet_build_latency_seconds"
FLEET_PEER_CHUNK_HITS = "makisu_fleet_peer_chunk_hits_total"
FLEET_PEER_CHUNK_MISSES = "makisu_fleet_peer_chunk_misses_total"
FLEET_PEER_CHUNK_BYTES = "makisu_fleet_peer_chunk_bytes_total"
FLEET_PEER_MAP_VERSION = "makisu_fleet_peer_map_version"
FLEET_CHUNK_SERVES = "makisu_fleet_chunk_serves_total"
FLEET_CHUNK_SERVE_BYTES = "makisu_fleet_chunk_serve_bytes_total"

# Chunk-native distribution plane (makisu_tpu/serve/): one name set
# shared by the recipe store, the serve/worker endpoints, the delta-pull
# client, the peer pack exchange, loadgen's fleet report, and the docs'
# metric table. Recipe/pack request counters label result/kind; the
# delta byte counters split a pull's economics into wire-fetched vs
# locally-reused bytes.
SERVE_RECIPES_PUBLISHED = "makisu_serve_recipes_published_total"
SERVE_RECIPE_REQUESTS = "makisu_serve_recipe_requests_total"
SERVE_PACK_REQUESTS = "makisu_serve_pack_requests_total"
SERVE_PACK_BYTES = "makisu_serve_pack_bytes_total"
SERVE_DELTA_PULLS = "makisu_serve_delta_pulls_total"
SERVE_DELTA_BYTES = "makisu_serve_delta_bytes_total"
SERVE_PEER_PACK_REQUESTS = "makisu_serve_peer_pack_requests_total"
SERVE_PEER_PACK_BYTES = "makisu_serve_peer_pack_bytes_total"
# Seekable-zstd pack plane: independently-decompressible frames served
# (the /zpacks endpoint), and wire bytes split by encoding — the
# raw-vs-compressed economics the delta-pull smoke gates on
# (encoding=raw|zstd, counted client-side as fetched and server-side
# as served).
SERVE_PACK_FRAMES = "makisu_serve_pack_frames_total"
SERVE_WIRE_BYTES = "makisu_serve_wire_bytes_total"

# Deploy-identity info gauge (cli.main): constant 1, identity in the
# labels — the node_exporter "build_info" idiom.
BUILD_INFO = "makisu_build_info"

# Registry transfer plane (registry/client.py): bytes/blobs count the
# wire in both directions; retries label the retried operation.
REGISTRY_BYTES_TOTAL = "makisu_registry_bytes_total"
REGISTRY_BLOBS_TOTAL = "makisu_registry_blobs_total"
REGISTRY_RETRIES_TOTAL = "makisu_registry_retries_total"

# HTTP transport (utils/httputil.py): requests vs fresh connections —
# the keep-alive reuse ratio CI's transfer smoke asserts on.
HTTP_REQUESTS_TOTAL = "makisu_http_requests_total"
HTTP_CONNECTIONS_TOTAL = "makisu_http_connections_total"

# Process resource gauges (utils/resources.py sampler): what the
# worker's /metrics scrape sees between builds.
PROCESS_RSS_BYTES = "makisu_process_rss_bytes"
PROCESS_CPU_SECONDS = "makisu_process_cpu_seconds"
PROCESS_THREADS = "makisu_process_threads"
PROCESS_OPEN_FDS = "makisu_process_open_fds"
PROCESS_IO_READ_BYTES = "makisu_process_io_read_bytes"
PROCESS_IO_WRITE_BYTES = "makisu_process_io_write_bytes"

# Build-plan execution (builder/plan.py, builder/node.py).
STAGES_TOTAL = "makisu_stages_total"
CACHED_LAYERS_APPLIED_TOTAL = "makisu_cached_layers_applied_total"

# Resident build sessions (worker/session.py): reuse hits, dirty-set
# invalidations by reason, and resident memo bytes per context.
SESSION_HITS = "makisu_session_hits"
SESSION_INVALIDATIONS = "makisu_session_invalidations_total"
SESSION_RESIDENT_BYTES = "makisu_session_resident_bytes"

# Chunk-addressed session snapshots (worker/snapshots.py): checkpoint
# writes (result=ok|error), chunk bytes pushed into the CAS split by
# result=written|reused (the O(changed) incremental-write economics),
# and restore attempts labeled result=ok|refused|error — refusals
# carry the invalidation reason (flag_identity|isa_change|stale|...)
# so a fleet that silently falls back to cold rebuilds still pages.
SESSION_SNAPSHOT_WRITES = "makisu_session_snapshot_writes_total"
SESSION_SNAPSHOT_CHUNK_BYTES = "makisu_session_snapshot_chunk_bytes_total"
SESSION_SNAPSHOT_RESTORES = "makisu_session_snapshot_restores_total"

# Fleet-wide trace stitching: inbound traceparent adoption outcomes
# (result=adopted|malformed — a malformed header mints fresh ids and
# is COUNTED, never crashed on), and the front door's aggregated
# /metrics fan-out (result=ok|error per worker scrape).
TRACE_ADOPTED = "makisu_trace_adopted_total"
FLEET_AGGREGATED_SCRAPES = "makisu_fleet_aggregated_scrapes_total"

# Serve access ledger (serve/server.py AccessLog): per-request rows
# keyed by the inbound traceparent, the cross-process half of a peer/
# delta fetch's trace. The counter tallies rows by kind so the ring's
# churn is visible on /metrics.
SERVE_ACCESS_TOTAL = "makisu_serve_access_total"

# Storage observability plane (cache/census.py): per-plane census
# gauges (plane=blobs|chunks|packs|recipes), per-tenant attribution
# (tenant labels capped via census.cap_label), audit findings by kind,
# and the sampled integrity scrub's progress/corruption counters.
STORAGE_BYTES = "makisu_storage_bytes"
STORAGE_OBJECTS = "makisu_storage_objects"
STORAGE_TENANT_BYTES = "makisu_storage_tenant_bytes"
STORAGE_FINDINGS = "makisu_storage_findings"
STORAGE_CENSUS_RUNS = "makisu_storage_census_runs_total"
STORAGE_SCRUB_CHUNKS = "makisu_storage_scrub_chunks_total"
STORAGE_SCRUB_BYTES = "makisu_storage_scrub_bytes_total"
STORAGE_SCRUB_CORRUPT = "makisu_storage_scrub_corrupt_total"

# Storage mechanism plane (storage/contentstore.py): the budget
# evictor's victims by reason (lru|quota|demote|demote_pack), per-tier
# byte gauges (tier=hot|pack|remote), and bytes moved back by
# pack-tier refetch promotions.
STORAGE_EVICTIONS = "makisu_storage_evictions_total"
STORAGE_TIER_BYTES = "makisu_storage_tier_bytes"
STORAGE_REFETCH_BYTES = "makisu_storage_refetch_bytes_total"

# Fleet SLO plane (fleet/slo.py + utils/alerts.py): alert lifecycle
# counters (labeled rule/severity), the active-alert gauge a threshold
# rule or dashboard reads directly, webhook delivery outcomes
# (result=ok|error), synthetic canary build outcomes
# (worker + result=ok|error) and latency, the per-worker health score
# the scheduler's demotion reads, and the scrape-fan-out liveness
# gauge (1/0 per worker) on the aggregated fleet /metrics.
ALERTS_FIRED = "makisu_alerts_fired_total"
ALERTS_RESOLVED = "makisu_alerts_resolved_total"
ALERT_ACTIVE = "makisu_alert_active"
ALERT_WEBHOOK = "makisu_alert_webhook_total"
CANARY_BUILDS = "makisu_canary_builds_total"
CANARY_LATENCY = "makisu_canary_latency_seconds"
WORKER_HEALTH_SCORE = "makisu_worker_health_score"
WORKER_UP = "makisu_worker_up"

# Continuous profiling plane: the wall-clock sampler's own vitals —
# cumulative samples, folded stacks dropped at the bounded-memory cap,
# distinct stacks held, and the self-measured overhead fraction the
# <2% budget is judged against. Exported ~1/s from the sampler thread.
PROFILER_SAMPLES = "makisu_profiler_samples_total"
PROFILER_DROPPED = "makisu_profiler_dropped_total"
PROFILER_STACKS = "makisu_profiler_distinct_stacks"
PROFILER_OVERHEAD = "makisu_profiler_overhead_ratio"


def stage_busy_add(stage: str, seconds: float) -> None:
    """Charge ``seconds`` of busy time to one commit-pipeline stage.
    Callers accumulate locally and flush per batch/close — never per
    chunk — so the accounting can't become the overhead it measures."""
    counter_add(COMMIT_STAGE_BUSY, seconds, stage=stage)


def stage_queue_depth(stage: str, depth: int) -> None:
    gauge_set(COMMIT_QUEUE_DEPTH, depth, stage=stage)


def _label_key(labels: dict[str, Any]) -> _LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _nearest_rank(ordered, p: float) -> float:
    rank = max(int(len(ordered) * p / 100.0 + 0.5), 1)
    return ordered[min(rank, len(ordered)) - 1]


def percentile(values, p: float) -> float:
    """Nearest-rank percentile of a sequence (p in [0, 100]). One
    definition shared by the worker's queue stats, loadgen's report,
    the history trends, and bench's warm-rebuild rounds — four
    consumers quoting p50/p99 must agree on what those mean. Raises
    on an empty sequence (callers gate on count)."""
    ordered = sorted(values)
    if not ordered:
        raise ValueError("percentile of an empty sequence")
    return _nearest_rank(ordered, p)


def percentile_stats(values) -> dict[str, float]:
    """``{"count", "p50", "p90", "p99", "max"}`` of a sequence —
    the latency digest every load-observability surface exports
    (``/healthz`` queue section, ``/builds``, loadgen reports,
    ``history`` trends). Empty input yields ``{"count": 0}``. One
    sort serves all three ranks."""
    ordered = sorted(values)
    if not ordered:
        return {"count": 0}
    return {
        "count": len(ordered),
        "p50": round(_nearest_rank(ordered, 50), 6),
        "p90": round(_nearest_rank(ordered, 90), 6),
        "p99": round(_nearest_rank(ordered, 99), 6),
        "max": round(ordered[-1], 6),
    }


def new_id(nbytes: int) -> str:
    """Random lowercase-hex identifier of ``2 * nbytes`` characters.
    W3C trace ids are 16 bytes, span ids 8 (trace-context §3.2.2.3-4)."""
    return os.urandom(nbytes).hex()


def parse_traceparent(value: str) -> tuple[str, str] | None:
    """Validate a W3C ``traceparent`` header value and return
    ``(trace_id, parent_span_id)``, or ``None`` for anything
    malformed. Strict by the spec's §3.2: four dash-separated fields,
    a known 2-hex version (``ff`` is reserved-invalid), 32/16
    lowercase-hex ids, neither all-zero, a 2-hex flags field. Callers
    MUST mint fresh ids on ``None`` — a bad header from a buggy proxy
    can cost stitching, never a build."""
    if not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    hexdigits = set("0123456789abcdef")
    for field, width in ((version, 2), (trace_id, 32),
                         (span_id, 16), (flags, 2)):
        if len(field) != width or not set(field) <= hexdigits:
            return None
    if version == "ff":
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


# Inbound trace context for the NEXT registry this context creates:
# the worker's /build handler (and anything else accepting a build on
# behalf of an upstream caller) binds the raw traceparent here, and
# ``cli.main`` adopts it into the build's fresh registry — so the
# front door's forward span, the worker's build spans, and every
# outbound request the build issues share ONE trace id.
_inbound_traceparent: "contextvars.ContextVar[str]" = \
    contextvars.ContextVar("makisu_inbound_traceparent", default="")


def bind_inbound_traceparent(value: str):
    """Bind a raw inbound ``traceparent`` in the current context
    (validated only at adoption time). Returns a reset token."""
    return _inbound_traceparent.set(value or "")


def reset_inbound_traceparent(token) -> None:
    _inbound_traceparent.reset(token)


def inbound_traceparent() -> str:
    return _inbound_traceparent.get()


def adopt_inbound(registry: "MetricsRegistry", value: str) -> str:
    """Adopt a raw inbound traceparent into ``registry`` — THE
    adoption policy, shared by ``cli.main`` and the fleet forwarder so
    the semantics (and the ``makisu_trace_adopted_total`` counting)
    can never diverge between the two doors. Returns ``"adopted"``,
    ``"malformed"`` (fresh ids kept, counted), or ``""`` (no inbound
    value at all)."""
    if not value:
        return ""
    parsed = parse_traceparent(value)
    if parsed is None:
        _global.counter_add(TRACE_ADOPTED, result="malformed")
        return "malformed"
    registry.adopt_trace(*parsed)
    _global.counter_add(TRACE_ADOPTED, result="adopted")
    return "adopted"


class Span:
    """One timed operation; children nest via the context variable.

    Every span carries a W3C-shaped 64-bit span id and its parent's, so
    the tree exports losslessly (Perfetto, the event stream) and the
    ``traceparent`` header on outbound HTTP names the exact span that
    issued the request."""

    __slots__ = ("name", "attrs", "start_unix", "duration", "error",
                 "children", "registry", "span_id", "parent_id", "_t0",
                 "peak_rss", "cpu_seconds")

    def __init__(self, name: str, attrs: dict[str, Any],
                 registry: "MetricsRegistry") -> None:
        self.name = name
        self.attrs = {k: str(v) for k, v in attrs.items()}
        self.start_unix = time.time()
        self._t0 = time.monotonic()
        self.duration: float | None = None  # None while still open
        self.error: str | None = None
        self.children: list[Span] = []
        self.registry = registry
        self.span_id = new_id(8)
        self.parent_id = ""
        # Filled by the resource sampler (utils/resources.py) while the
        # span is open: peak process RSS observed, and the CPU seconds
        # charged to this span while it was an open LEAF. None = never
        # sampled (sampler off, or span shorter than the interval).
        self.peak_rss: int | None = None
        self.cpu_seconds = 0.0

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "name": self.name,
            "span_id": self.span_id,
            "start": round(self.start_unix, 6),
            "duration": (round(self.duration, 6)
                         if self.duration is not None else None),
        }
        if self.parent_id:
            out["parent_id"] = self.parent_id
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        if self.error:
            out["error"] = self.error
        if self.peak_rss is not None:
            out["resources"] = {
                "peak_rss_bytes": int(self.peak_rss),
                "cpu_seconds": round(self.cpu_seconds, 6),
            }
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        return out


class _Hist:
    __slots__ = ("count", "sum", "min", "max", "buckets", "bucket_counts")

    def __init__(self, buckets: tuple[float, ...]) -> None:
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self.buckets = buckets
        self.bucket_counts = [0] * len(buckets)

    def observe(self, value: float) -> None:
        self.count += 1
        self.sum += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        # bucket_counts are per-bucket (NON-cumulative); the Prometheus
        # renderer prefix-sums them into the cumulative form.
        for i, le in enumerate(self.buckets):
            if value <= le:
                self.bucket_counts[i] += 1
                break


class MetricsRegistry:
    """Counters/gauges/histograms plus a span-tree root. Thread-safe."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, dict[_LabelKey, float]] = {}
        self._gauges: dict[str, dict[_LabelKey, float]] = {}
        self._hists: dict[str, dict[_LabelKey, _Hist]] = {}
        # One 128-bit trace id per registry: every span in this
        # registry's tree — and every traceparent header a request in
        # its context carries — shares it, so a build's outbound HTTP
        # is correlatable with registry/KV server logs.
        self.trace_id = new_id(16)
        self.root = Span("root", {}, self)

    def adopt_trace(self, trace_id: str, parent_span_id: str) -> None:
        """Adopt an upstream trace context (a validated traceparent):
        this registry's spans join the caller's trace instead of
        minting a fresh one. The ROOT span takes the caller's span id,
        so the first real span this registry opens carries
        ``parent_id = <caller's span>`` — the cross-process stitch a
        merged trace assembles on. Call before any span opens (the
        adoption point in ``cli.main`` is right after the registry is
        bound)."""
        self.trace_id = trace_id
        self.root.span_id = parent_span_id

    # -- writes -----------------------------------------------------------

    def counter_add(self, name: str, value: float = 1.0,
                    **labels: Any) -> None:
        key = _label_key(labels)
        # Signal-context callers (FlightRecorder.dump) PROBE this lock
        # with a timeout first and skip the bump when it is held — see
        # the `for reg in metrics._targets()` guard in dump().
        # check: allow(signal-safety)
        with self._lock:
            series = self._counters.setdefault(name, {})
            series[key] = series.get(key, 0.0) + value

    def gauge_set(self, name: str, value: float, **labels: Any) -> None:
        with self._lock:
            self._gauges.setdefault(name, {})[_label_key(labels)] = \
                float(value)

    def observe(self, name: str, value: float,
                buckets: tuple[float, ...] | None = None,
                **labels: Any) -> None:
        key = _label_key(labels)
        with self._lock:
            series = self._hists.setdefault(name, {})
            hist = series.get(key)
            if hist is None:
                hist = series[key] = _Hist(buckets or DEFAULT_BUCKETS)
            hist.observe(value)

    def observe_batch(self, name: str, values,
                      buckets: tuple[float, ...] | None = None,
                      **labels: Any) -> None:
        """Fold a whole batch of observations into one histogram under
        ONE lock acquisition. The per-value path costs a lock + label
        sort each; a 4GB layer has ~500k chunk sizes to observe, which
        must not become the overhead the histogram exists to measure.
        Binning runs outside the lock."""
        values = list(values)
        if not values:
            return
        use = buckets or DEFAULT_BUCKETS
        import bisect
        binned = [0] * len(use)
        for v in values:
            i = bisect.bisect_left(use, v)
            if i < len(use):
                binned[i] += 1
        total, lo, hi = float(sum(values)), min(values), max(values)
        key = _label_key(labels)
        with self._lock:
            series = self._hists.setdefault(name, {})
            hist = series.get(key)
            if hist is None:
                hist = series[key] = _Hist(use)
            hist.count += len(values)
            hist.sum += total
            hist.min = lo if hist.min is None else min(hist.min, lo)
            hist.max = hi if hist.max is None else max(hist.max, hi)
            if hist.buckets == use:
                for i, n in enumerate(binned):
                    hist.bucket_counts[i] += n
            else:  # first observer picked other buckets; re-bin to its
                for v in values:
                    for i, le in enumerate(hist.buckets):
                        if v <= le:
                            hist.bucket_counts[i] += 1
                            break

    # -- reads ------------------------------------------------------------

    def counter_total(self, name: str, **labels: Any) -> float:
        """Sum of every series of ``name`` whose labels are a superset
        of the given ones (no labels: the metric's grand total)."""
        want = set(_label_key(labels))
        with self._lock:
            series = self._counters.get(name, {})
            return sum(v for k, v in series.items() if want <= set(k))

    def gauge_value(self, name: str, default: float = 0.0,
                    **labels: Any) -> float:
        """Current value of one gauge series (exact label match; no
        labels reads the unlabeled series). What the worker's
        ``/healthz`` uses to surface transfer-engine gauges without a
        Prometheus scrape."""
        with self._lock:
            return self._gauges.get(name, {}).get(
                _label_key(labels), default)

    def counter_by_label(self, name: str, label: str) -> dict[str, float]:
        """Grand total of ``name`` broken down by one label's values."""
        out: dict[str, float] = {}
        with self._lock:
            for key, value in self._counters.get(name, {}).items():
                for k, v in key:
                    if k == label:
                        out[v] = out.get(v, 0.0) + value
        return out

    def report(self) -> dict[str, Any]:
        """JSON-ready build report: span tree + every metric series."""

        def series_list(table: dict[str, dict[_LabelKey, float]]):
            return {
                name: [{"labels": dict(key), "value": value}
                       for key, value in sorted(series.items())]
                for name, series in sorted(table.items())
            }

        # Signal-context callers reach report() only through
        # flightrecorder._metrics_snapshot, which probes this lock with
        # a timeout and ships the bundle without a metrics section when
        # it is held.  # check: allow(signal-safety)
        with self._lock:
            hists = {
                name: [{
                    "labels": dict(key),
                    "count": h.count,
                    "sum": round(h.sum, 6),
                    "min": h.min,
                    "max": h.max,
                } for key, h in sorted(series.items())]
                for name, series in sorted(self._hists.items())
            }
            counters = series_list(self._counters)
            gauges = series_list(self._gauges)
            spans = [c.to_dict() for c in self.root.children]
        return {
            "schema": "makisu-tpu.metrics.v1",
            "trace_id": self.trace_id,
            "spans": spans,
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
        }


# -- scoping ---------------------------------------------------------------

_global = MetricsRegistry()

_build_registry: "contextvars.ContextVar[MetricsRegistry | None]" = \
    contextvars.ContextVar("makisu_build_metrics", default=None)
_current_span: "contextvars.ContextVar[Span | None]" = \
    contextvars.ContextVar("makisu_current_span", default=None)


def global_registry() -> MetricsRegistry:
    return _global


def set_build_registry(registry: MetricsRegistry | None):
    """Bind a per-context registry (worker mode: one per /build).
    Returns a token for ``reset_build_registry``."""
    return _build_registry.set(registry)


def reset_build_registry(token) -> None:
    _build_registry.reset(token)


def active_registry() -> MetricsRegistry:
    return _build_registry.get() or _global


def _targets() -> tuple[MetricsRegistry, ...]:
    bound = _build_registry.get()
    if bound is None or bound is _global:
        return (_global,)
    return (_global, bound)


# Every span open in the process, across all registries: the resource
# sampler attributes RSS/CPU to these, and the flight recorder snapshots
# them (with ages) into diagnostic bundles. A plain dict keyed by id():
# single-item inserts/deletes are atomic under the GIL, so readers —
# including a SIGTERM handler that interrupted arbitrary code — never
# need a lock that the interrupted frame might hold.
_open_spans: dict[int, Span] = {}


def snapshot_concurrent(container) -> list:
    """``list(container)`` against a structure other threads mutate
    WITHOUT taking a lock: retried on the RuntimeError a concurrent
    resize raises, empty after four straight losses. The forensics
    paths (signal handlers included) read every shared structure this
    way — a lock the interrupted frame might hold must never be
    taken."""
    for _ in range(4):
        try:
            return list(container)
        except RuntimeError:  # mutated mid-iteration; retry
            continue
    return []  # pragma: no cover - four consecutive races


def open_span_snapshot() -> list[dict[str, Any]]:
    """Every open span as a JSON-ready dict with its age, sorted
    oldest-first. ``leaf`` marks spans with no open child — where the
    build actually is. Lock-free (retried on concurrent mutation) so
    the flight recorder can call it from a signal handler."""
    spans = snapshot_concurrent(_open_spans.values())
    now = time.monotonic()
    parent_ids = {s.parent_id for s in spans}
    out = []
    for s in spans:
        out.append({
            "name": s.name,
            "span_id": s.span_id,
            "parent_id": s.parent_id,
            "trace_id": s.registry.trace_id,
            "start": round(s.start_unix, 6),
            "age_seconds": round(now - s._t0, 3),
            "attrs": dict(s.attrs),
            "leaf": s.span_id not in parent_ids,
        })
    out.sort(key=lambda d: -d["age_seconds"])
    return out


def attribute_resource_sample(rss_bytes: int, cpu_delta: float) -> None:
    """Charge one resource sample to the open spans: every open span
    tracks the peak RSS observed while it was open; the CPU burned
    since the previous sample is split evenly across the open LEAF
    spans (concurrent builds share the process's CPU — an even split
    is the honest default). Called by ``utils/resources.py``."""
    spans = snapshot_concurrent(_open_spans.values())
    if not spans:
        return
    parent_ids = {s.parent_id for s in spans}
    leaves = [s for s in spans if s.span_id not in parent_ids]
    share = cpu_delta / len(leaves) if leaves else 0.0
    for s in spans:
        if s.peak_rss is None or rss_bytes > s.peak_rss:
            s.peak_rss = rss_bytes
    for s in leaves:
        s.cpu_seconds += share


def counter_add(name: str, value: float = 1.0, **labels: Any) -> None:
    for reg in _targets():
        reg.counter_add(name, value, **labels)


def gauge_set(name: str, value: float, **labels: Any) -> None:
    for reg in _targets():
        reg.gauge_set(name, value, **labels)


def observe(name: str, value: float,
            buckets: tuple[float, ...] | None = None,
            **labels: Any) -> None:
    for reg in _targets():
        reg.observe(name, value, buckets=buckets, **labels)


def observe_batch(name: str, values,
                  buckets: tuple[float, ...] | None = None,
                  **labels: Any) -> None:
    values = list(values)
    for reg in _targets():
        reg.observe_batch(name, values, buckets=buckets, **labels)


@contextlib.contextmanager
def span(name: str, **attrs: Any) -> Iterator[Span]:
    """Timed scope attached to the innermost bound registry's tree.
    Nested spans become children; exceptions mark the span and
    propagate (telemetry never swallows a build failure). Open/close
    mirror onto the build event bus (no-op unless a sink is bound)."""
    reg = active_registry()
    parent = _current_span.get()
    if parent is None or parent.registry is not reg:
        parent = reg.root
    s = Span(name, attrs, reg)
    s.parent_id = parent.span_id
    with reg._lock:
        parent.children.append(s)
    _open_spans[id(s)] = s
    token = _current_span.set(s)
    # trace_id rides every span event so a multi-build event stream
    # (a worker's global sinks, the fleet front door's merged log) can
    # be partitioned back into per-trace span trees.
    events.emit("span_start", name=name, span_id=s.span_id,
                parent_id=s.parent_id, trace_id=reg.trace_id,
                **({"attrs": s.attrs} if s.attrs else {}))
    try:
        yield s
    except BaseException as e:
        s.error = f"{type(e).__name__}: {e}"
        raise
    finally:
        s.duration = time.monotonic() - s._t0
        _open_spans.pop(id(s), None)
        _current_span.reset(token)
        events.emit("span_end", name=name, span_id=s.span_id,
                    duration=round(s.duration, 6),
                    trace_id=reg.trace_id,
                    **({"error": s.error} if s.error else {}))


def has_trace_context() -> bool:
    """Whether this context carries an EXPLICIT trace identity — a
    bound per-build registry or an open span. Build-submission paths
    (``WorkerClient.build``) attach a ``traceparent`` only then: the
    process-global registry's id is fine for attributing stray HTTP,
    but adopting it for a build would merge every build a bare
    process submits into one trace (and two concurrent submissions
    into each other's)."""
    return (_build_registry.get() is not None
            or _current_span.get() is not None)


def current_traceparent() -> str:
    """W3C ``traceparent`` header value for the innermost open span of
    the active registry: ``00-<trace-id>-<span-id>-01``. With no span
    open, the registry's root span id is used — every outbound request
    is attributable to a trace even outside a build."""
    reg = active_registry()
    s = _current_span.get()
    if s is None or s.registry is not reg:
        s = reg.root
    return f"00-{reg.trace_id}-{s.span_id}-01"


# -- renderers -------------------------------------------------------------


def _escape(value: str) -> str:
    return (value.replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt_value(value: float) -> str:
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(value)


def _fmt_labels(key: _LabelKey, extra: tuple[tuple[str, str], ...] = ()
                ) -> str:
    pairs = key + extra
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in pairs)
    return "{" + inner + "}"


def render_prometheus(registry: MetricsRegistry | None = None) -> str:
    """Prometheus text exposition (format 0.0.4) of a registry —
    default: the process-global one (what ``GET /metrics`` serves)."""
    reg = registry if registry is not None else _global
    lines: list[str] = []
    with reg._lock:
        for name in sorted(reg._counters):
            lines.append(f"# TYPE {name} counter")
            for key, value in sorted(reg._counters[name].items()):
                lines.append(f"{name}{_fmt_labels(key)} "
                             f"{_fmt_value(value)}")
        for name in sorted(reg._gauges):
            lines.append(f"# TYPE {name} gauge")
            for key, value in sorted(reg._gauges[name].items()):
                lines.append(f"{name}{_fmt_labels(key)} "
                             f"{_fmt_value(value)}")
        for name in sorted(reg._hists):
            lines.append(f"# TYPE {name} histogram")
            for key, h in sorted(reg._hists[name].items()):
                cumulative = 0
                for le, n in zip(h.buckets, h.bucket_counts):
                    cumulative += n
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(key, (('le', _fmt_value(le)),))} "
                        f"{cumulative}")
                lines.append(
                    f"{name}_bucket{_fmt_labels(key, (('le', '+Inf'),))}"
                    f" {h.count}")
                lines.append(f"{name}_sum{_fmt_labels(key)} "
                             f"{_fmt_value(h.sum)}")
                lines.append(f"{name}_count{_fmt_labels(key)} {h.count}")
    return "\n".join(lines) + ("\n" if lines else "")


def relabel_prometheus(text: str, **labels: str) -> str:
    """Inject labels into every sample line of a Prometheus text
    exposition — how the fleet front door re-exports each worker's
    scrape under a ``worker="wN"`` label so one Prometheus target sees
    the whole fleet. Comment/TYPE lines pass through unchanged; sample
    lines gain the labels FIRST (`name{worker="w0",...} value`), both
    the brace-less and labeled forms. Injected labels are
    operator-controlled (worker ids), so no escaping beyond the
    standard one is needed."""
    if not labels:
        return text
    inject = ",".join(f'{k}="{_escape(str(v))}"'
                      for k, v in sorted(labels.items()))
    out: list[str] = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        name, sep, rest = line.partition("{")
        if sep:
            out.append(f"{name}{{{inject},{rest}")
        else:
            name, _, value = line.partition(" ")
            out.append(f"{name}{{{inject}}} {value}")
    return "\n".join(out) + ("\n" if out else "")


def merge_prometheus(parts: list[str]) -> str:
    """Merge several Prometheus text expositions into ONE valid one:
    all samples of a metric family end up in a single group under a
    single ``# TYPE`` line (the format forbids split groups — naive
    concatenation of N scrapes is exactly that). Histogram samples
    (``_bucket``/``_sum``/``_count``) fold into their declared family.
    First TYPE declaration wins; family order is first-seen."""
    order: list[str] = []
    type_line: dict[str, str] = {}
    samples: dict[str, list[str]] = {}
    histograms: set[str] = set()
    # Pass 1: every declared histogram family (so pass 2 can fold
    # suffixed samples even when they appear before/without their own
    # part's TYPE line).
    for text in parts:
        for line in text.splitlines():
            if line.startswith("# TYPE "):
                fields = line.split()
                if len(fields) >= 4 and fields[3] == "histogram":
                    histograms.add(fields[2])

    def family_of(name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) \
                    and name[:-len(suffix)] in histograms:
                return name[:-len(suffix)]
        return name

    for text in parts:
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("# TYPE "):
                fields = line.split()
                if len(fields) >= 3:
                    type_line.setdefault(fields[2], line)
                continue
            if line.startswith("#"):
                continue
            name = line.partition("{")[0].partition(" ")[0]
            family = family_of(name)
            if family not in samples:
                samples[family] = []
                order.append(family)
            samples[family].append(line)
    out: list[str] = []
    for family in order:
        if family in type_line:
            out.append(type_line[family])
        out.extend(samples[family])
    return "\n".join(out) + ("\n" if out else "")


def summary(registry: MetricsRegistry | None = None) -> dict[str, Any]:
    """Flat key/value digest of one build's registry — the fields the
    final ``info("build telemetry", ...)`` line carries."""
    reg = registry if registry is not None else active_registry()
    out: dict[str, Any] = {}
    with reg._lock:
        top = reg.root.children[0] if reg.root.children else None
    duration = top.duration if top is not None else None
    if duration is not None:
        out["duration_seconds"] = round(duration, 3)
    out["cache_hits"] = int(reg.counter_total(
        "makisu_cache_pull_total", result="hit"))
    out["cache_misses"] = int(reg.counter_total(
        "makisu_cache_pull_total", result="miss"))
    out["layers_committed"] = int(reg.counter_total(
        "makisu_layer_commits_total"))
    hashed = reg.counter_by_label("makisu_bytes_hashed_total", "backend")
    for backend, nbytes in sorted(hashed.items()):
        out[f"hashed_bytes_{backend}"] = int(nbytes)
    total_hashed = sum(hashed.values())
    out["hashed_bytes"] = int(total_hashed)
    if duration:
        out["hashed_bytes_per_sec"] = int(total_hashed / duration)
    out["registry_pull_bytes"] = int(reg.counter_total(
        "makisu_registry_bytes_total", direction="pull"))
    out["registry_push_bytes"] = int(reg.counter_total(
        "makisu_registry_bytes_total", direction="push"))
    return out


def write_report(path: str,
                 registry: MetricsRegistry | None = None,
                 **extra: Any) -> None:
    """Write a build's JSON telemetry report (the ``--metrics-out``
    payload): span tree + counters, plus any caller extras (exit code,
    argv). Atomic: tmp file + ``os.replace``, so a build killed
    mid-write never leaves a torn half-JSON report behind."""
    reg = registry if registry is not None else active_registry()
    payload = reg.report()
    payload.update(extra)
    write_json_atomic(path, payload)


def write_json_atomic(path: str, payload: Any) -> None:
    """Atomically serialize ``payload`` as JSON to ``path``. The tmp
    name carries the pid so concurrent builds writing into one
    directory can't cross-clobber each other's staging files.
    ``default=str`` for the same reason the event sinks use it: a
    non-JSON-native span attr must degrade to its repr, not fail the
    invocation after the build itself succeeded."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=False,
                      default=str)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
