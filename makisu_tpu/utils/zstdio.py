"""Streaming zstd over the system libzstd via ctypes: decode + encode.

Registries increasingly publish base-image layers as
``application/vnd.oci.image.layer.v1.tar+zstd`` (containerd and buildkit
both default new pushes there for large images); the pull path used to
reject them up front in ``registry/client.py``. CPython grows a stdlib
``compression.zstd`` only in 3.14, and the sandbox must not pip-install
anything — but every mainstream distro ships ``libzstd.so.1``, and the
streaming surfaces (``ZSTD_decompressStream`` /
``ZSTD_compressStream2``) are a handful of calls each. This module
binds exactly those: a read-only file-like decoder and a write-only
file-like encoder, both with bounded memory (one input + one output
buffer of libzstd's recommended sizes), plus one-shot block
compress/decompress.

The compress side serves the **seekable pack** plane (serve/recipe.py):
packs are encoded as independently-decompressible frames so ranged
span fetches decompress without upstream context. LAYERS this builder
writes stay deterministic gzip — gzip cache identity and chunk
reconstitution are untouched; zstd output never enters a layer digest.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import io
import threading

# Zstandard frame magic (RFC 8878 §3.1.1): the sniff byte sequence the
# layer-reader uses to route a blob here instead of gzip.
MAGIC = b"\x28\xb5\x2f\xfd"

_lib = None
_lib_mu = threading.Lock()
_lib_failed = False


class _InBuffer(ctypes.Structure):
    _fields_ = [("src", ctypes.c_void_p),
                ("size", ctypes.c_size_t),
                ("pos", ctypes.c_size_t)]


class _OutBuffer(ctypes.Structure):
    _fields_ = [("dst", ctypes.c_void_p),
                ("size", ctypes.c_size_t),
                ("pos", ctypes.c_size_t)]


def _load():
    """Resolve libzstd once per process; a host without it degrades to
    available() == False (the caller keeps its clear rejection error)."""
    global _lib, _lib_failed
    with _lib_mu:
        if _lib is not None or _lib_failed:
            return _lib
        name = ctypes.util.find_library("zstd") or "libzstd.so.1"
        try:
            lib = ctypes.CDLL(name)
            lib.ZSTD_createDStream.restype = ctypes.c_void_p
            lib.ZSTD_freeDStream.argtypes = [ctypes.c_void_p]
            lib.ZSTD_initDStream.argtypes = [ctypes.c_void_p]
            lib.ZSTD_initDStream.restype = ctypes.c_size_t
            lib.ZSTD_decompressStream.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(_OutBuffer),
                ctypes.POINTER(_InBuffer)]
            lib.ZSTD_decompressStream.restype = ctypes.c_size_t
            lib.ZSTD_isError.argtypes = [ctypes.c_size_t]
            lib.ZSTD_isError.restype = ctypes.c_uint
            lib.ZSTD_getErrorName.argtypes = [ctypes.c_size_t]
            lib.ZSTD_getErrorName.restype = ctypes.c_char_p
            lib.ZSTD_DStreamInSize.restype = ctypes.c_size_t
            lib.ZSTD_DStreamOutSize.restype = ctypes.c_size_t
            # Compress side (streaming + one-shot). Every libzstd.so.1
            # since 1.4 exports these; a host whose library somehow
            # lacks one degrades the whole module to available()==False
            # rather than failing later mid-write.
            lib.ZSTD_createCStream.restype = ctypes.c_void_p
            lib.ZSTD_freeCStream.argtypes = [ctypes.c_void_p]
            lib.ZSTD_initCStream.argtypes = [ctypes.c_void_p,
                                             ctypes.c_int]
            lib.ZSTD_initCStream.restype = ctypes.c_size_t
            lib.ZSTD_compressStream2.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(_OutBuffer),
                ctypes.POINTER(_InBuffer), ctypes.c_int]
            lib.ZSTD_compressStream2.restype = ctypes.c_size_t
            lib.ZSTD_CStreamInSize.restype = ctypes.c_size_t
            lib.ZSTD_CStreamOutSize.restype = ctypes.c_size_t
            lib.ZSTD_compressBound.argtypes = [ctypes.c_size_t]
            lib.ZSTD_compressBound.restype = ctypes.c_size_t
            lib.ZSTD_compress.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t, ctypes.c_char_p,
                ctypes.c_size_t, ctypes.c_int]
            lib.ZSTD_compress.restype = ctypes.c_size_t
            lib.ZSTD_decompress.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t, ctypes.c_char_p,
                ctypes.c_size_t]
            lib.ZSTD_decompress.restype = ctypes.c_size_t
            _lib = lib
        except (OSError, AttributeError):
            _lib_failed = True
        return _lib


# ZSTD_EndDirective values for ZSTD_compressStream2.
_ZSTD_E_CONTINUE = 0
_ZSTD_E_END = 2


def available() -> bool:
    """Whether zstd decoding works in this process."""
    return _load() is not None


def is_zstd(prefix: bytes) -> bool:
    """Magic sniff on the first bytes of a blob."""
    return prefix[:4] == MAGIC


class ZstdReader(io.RawIOBase):
    """Read-only streaming decompressor over an inner file object.

    Memory stays bounded by libzstd's recommended buffer pair
    (~128KiB + ~128KiB) regardless of blob size; a truncated or
    corrupt frame raises ``ValueError`` — never silently short reads,
    because a short layer tar would corrupt the filesystem tree it is
    applied onto."""

    def __init__(self, fileobj) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "libzstd is not available in this process")
        self._lib = lib
        self._fh = fileobj
        self._stream = lib.ZSTD_createDStream()
        if not self._stream:
            raise MemoryError("ZSTD_createDStream failed")
        rc = lib.ZSTD_initDStream(self._stream)
        self._check(rc)
        self._in_cap = int(lib.ZSTD_DStreamInSize())
        self._out_cap = int(lib.ZSTD_DStreamOutSize())
        self._in_buf = ctypes.create_string_buffer(self._in_cap)
        self._out_buf = ctypes.create_string_buffer(self._out_cap)
        self._in = _InBuffer(
            ctypes.cast(self._in_buf, ctypes.c_void_p), 0, 0)
        # Decoded-but-unread bytes: bytearray + read offset so small
        # fixed-size reads (tarfile's 10KiB blocks) don't re-copy the
        # tail on every call.
        self._pending = bytearray()
        self._poff = 0
        self._eof = False
        # Nonzero between frames means "mid-frame" per the zstd API:
        # used to reject truncated input at EOF.
        self._last_rc = 0

    def _check(self, rc: int) -> int:
        if self._lib.ZSTD_isError(rc):
            raise ValueError(
                "zstd decode failed: "
                + self._lib.ZSTD_getErrorName(rc).decode(
                    errors="replace"))
        return rc

    def readable(self) -> bool:
        return True

    def _fill(self) -> bool:
        """Refill the input buffer from the inner file. Returns False
        at inner EOF with nothing buffered."""
        if self._in.pos < self._in.size:
            return True
        chunk = self._fh.read(self._in_cap)
        if not chunk:
            return False
        ctypes.memmove(self._in_buf, chunk, len(chunk))
        self._in.size = len(chunk)
        self._in.pos = 0
        return True

    def _decode_more(self) -> bytes:
        """One ZSTD_decompressStream round; b"" only at clean EOF."""
        while True:
            if not self._fill():
                if self._last_rc != 0:
                    raise ValueError(
                        "zstd stream truncated mid-frame")
                self._eof = True
                return b""
            out = _OutBuffer(
                ctypes.cast(self._out_buf, ctypes.c_void_p),
                self._out_cap, 0)
            rc = self._check(self._lib.ZSTD_decompressStream(
                self._stream, ctypes.byref(out),
                ctypes.byref(self._in)))
            self._last_rc = rc
            if out.pos:
                # string_at copies exactly out.pos bytes; .raw[:pos]
                # would copy the whole 128KiB buffer first.
                return ctypes.string_at(self._out_buf, out.pos)
            # No output this round (headers/skippable frame); loop.

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            pieces = [bytes(memoryview(self._pending)[self._poff:])]
            self._pending = bytearray()
            self._poff = 0
            while not self._eof:
                pieces.append(self._decode_more())
            return b"".join(pieces)
        while len(self._pending) - self._poff < n and not self._eof:
            chunk = self._decode_more()
            if chunk:
                if self._poff:
                    # Compact the consumed prefix only when growing, so
                    # the buffer stays ~one decode round deep and plain
                    # reads cost just the n bytes returned.
                    del self._pending[:self._poff]
                    self._poff = 0
                self._pending += chunk
        end = min(self._poff + n, len(self._pending))
        out = bytes(memoryview(self._pending)[self._poff:end])
        self._poff = end
        if self._poff == len(self._pending):
            self._pending = bytearray()
            self._poff = 0
        return out

    def close(self) -> None:
        if getattr(self, "_stream", None):
            self._lib.ZSTD_freeDStream(self._stream)
            self._stream = None
        super().close()

    def __enter__(self) -> "ZstdReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# Default compression level for pack frames: zstd's own default; wins
# most of the ratio at a fraction of the higher levels' CPU — the
# publish-time cost every indexed layer pays once.
DEFAULT_LEVEL = 3


def compress(data: bytes, level: int = DEFAULT_LEVEL) -> bytes:
    """One-shot block compression into a single complete zstd frame —
    the seekable-pack plane's frame encoder (each frame independently
    decompressible)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("libzstd is not available in this process")
    bound = int(lib.ZSTD_compressBound(len(data)))
    dst = ctypes.create_string_buffer(bound)
    rc = lib.ZSTD_compress(ctypes.cast(dst, ctypes.c_void_p), bound,
                           data, len(data), level)
    if lib.ZSTD_isError(rc):
        raise ValueError(
            "zstd compress failed: "
            + lib.ZSTD_getErrorName(rc).decode(errors="replace"))
    return dst.raw[:rc]


def decompress(data: bytes, expected_size: int) -> bytes:
    """One-shot frame decompression to exactly ``expected_size`` bytes.
    Truncated, corrupt, or wrong-sized frames raise ``ValueError`` —
    the pack-frame consumer treats any of those as a failed span and
    degrades, never installs short bytes."""
    lib = _load()
    if lib is None:
        raise RuntimeError("libzstd is not available in this process")
    dst = ctypes.create_string_buffer(max(expected_size, 1))
    rc = lib.ZSTD_decompress(ctypes.cast(dst, ctypes.c_void_p),
                             expected_size, data, len(data))
    if lib.ZSTD_isError(rc):
        raise ValueError(
            "zstd decode failed: "
            + lib.ZSTD_getErrorName(rc).decode(errors="replace"))
    if rc != expected_size:
        raise ValueError(
            f"zstd frame decoded to {rc} bytes, expected "
            f"{expected_size}")
    return dst.raw[:expected_size]


class ZstdWriter:
    """Write-only streaming compressor over an inner file object: the
    encode mirror of :class:`ZstdReader`. Memory stays bounded by
    libzstd's recommended buffer pair regardless of stream size;
    ``close()`` ends the frame (a stream abandoned before close is a
    truncated frame, which ZstdReader refuses — fail-stop, never a
    silently short artifact). One frame per writer."""

    def __init__(self, fileobj, level: int = DEFAULT_LEVEL) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "libzstd is not available in this process")
        self._lib = lib
        self._fh = fileobj
        self._stream = lib.ZSTD_createCStream()
        if not self._stream:
            raise MemoryError("ZSTD_createCStream failed")
        self._check(lib.ZSTD_initCStream(self._stream, level))
        self._out_cap = int(lib.ZSTD_CStreamOutSize())
        self._out_buf = ctypes.create_string_buffer(self._out_cap)
        self._closed = False
        self.compressed_size = 0  # bytes written downstream
        self.raw_size = 0         # bytes accepted

    def _check(self, rc: int) -> int:
        if self._lib.ZSTD_isError(rc):
            raise ValueError(
                "zstd encode failed: "
                + self._lib.ZSTD_getErrorName(rc).decode(
                    errors="replace"))
        return rc

    def _round(self, inbuf, directive: int) -> int:
        out = _OutBuffer(ctypes.cast(self._out_buf, ctypes.c_void_p),
                         self._out_cap, 0)
        rc = self._check(self._lib.ZSTD_compressStream2(
            self._stream, ctypes.byref(out), ctypes.byref(inbuf),
            directive))
        if out.pos:
            self._fh.write(ctypes.string_at(self._out_buf, out.pos))
            self.compressed_size += out.pos
        return rc

    def write(self, data) -> int:
        if self._closed:
            raise ValueError("write to a closed ZstdWriter")
        data = bytes(data)
        self.raw_size += len(data)
        inbuf = _InBuffer(
            ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p),
            len(data), 0)
        while inbuf.pos < inbuf.size:
            self._round(inbuf, _ZSTD_E_CONTINUE)
        return len(data)

    def flush(self) -> None:
        self._fh.flush()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        inbuf = _InBuffer(None, 0, 0)
        while self._round(inbuf, _ZSTD_E_END) != 0:
            pass
        self._lib.ZSTD_freeCStream(self._stream)
        self._stream = None

    def __del__(self) -> None:
        if getattr(self, "_stream", None):
            self._lib.ZSTD_freeCStream(self._stream)
            self._stream = None

    def __enter__(self) -> "ZstdWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
