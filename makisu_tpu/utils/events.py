"""Build event bus: the durable, streamable record of one build.

Spans (utils/metrics.py) answer "how long"; events answer "what
happened, in order, with what identity" — and unlike the span tree,
which only materializes when the build ends, events leave the process
the moment they occur. Three consumers:

- ``--events-out FILE``: a per-build JSONL event log (one JSON object
  per line), written through :class:`JsonlWriter`.
- The worker's ``/build`` response stream: each event rides as its own
  NDJSON frame (``{"event": {...}}``), interleaved with log-line
  frames, so a client watches a build's structure live.
- Tests/tools: any callable sink.

Scoping mirrors the per-build log sink in ``utils/logging.py`` and the
metrics contextvar: sinks bind to the current context, threads a build
spawns inherit them via ``contextvars.copy_context``, and concurrent
worker builds never see each other's events. With no sink bound,
``emit`` is a tuple-read no-op — instrumentation sites pay nothing.

Event shape: ``{"ts": <unix seconds>, "type": <str>, ...fields}``.
Types emitted today: ``build_start``/``build_end`` (cli.py),
``span_start``/``span_end`` (metrics.span), ``step`` (builder/stage.py,
``phase=start|done``), ``cache`` (cache/manager.py + cache/chunks.py,
``result=hit|miss|empty``), ``chunk_fetch`` (cache/chunks.py), and
``registry_blob`` (registry/client.py). The set is open: any module may
emit new types; consumers must ignore types they don't know.

Like the rest of the telemetry layer: stdlib-only, import-cycle-free,
and never able to fail a build — a raising sink is swallowed.
"""

from __future__ import annotations

import contextvars
import json
import threading
import time
from typing import Any, Callable

EventSink = Callable[[dict], None]

_sinks: "contextvars.ContextVar[tuple[EventSink, ...]]" = \
    contextvars.ContextVar("makisu_event_sinks", default=())


def add_sink(sink: EventSink):
    """Bind an event sink in the current context (stacking on any
    already bound). Returns a token for :func:`reset_sink`."""
    return _sinks.set(_sinks.get() + (sink,))


def reset_sink(token) -> None:
    _sinks.reset(token)


def active() -> bool:
    """Whether any sink is bound in this context (lets callers skip
    building expensive event payloads)."""
    return bool(_sinks.get())


def emit(event_type: str, **fields: Any) -> None:
    """Deliver one event to every bound sink. No sink: free no-op.
    A sink that raises is ignored — events must never fail a build."""
    sinks = _sinks.get()
    if not sinks:
        return
    event: dict[str, Any] = {"ts": round(time.time(), 6),
                             "type": event_type}
    event.update(fields)
    for sink in sinks:
        try:
            sink(event)
        except Exception:  # noqa: BLE001 - a dead sink must not kill a build
            pass


class JsonlWriter:
    """Append-only JSONL event sink (the ``--events-out`` file).

    Each event is one line, written and flushed under a lock so the
    concurrent writers a build spawns (cache pushes, chunk uploads,
    shell drains) can't interleave partial lines — a killed build
    leaves at worst one truncated FINAL line, and every line before it
    stays valid JSON."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._f = open(path, "w", encoding="utf-8")
        self._lock = threading.Lock()
        self._closed = False

    def __call__(self, event: dict) -> None:
        line = json.dumps(event, separators=(",", ":"), default=str)
        with self._lock:
            if self._closed:
                return
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                self._f.close()


def read_jsonl(path: str, skip_invalid: bool = False) -> list[dict]:
    """Load an event log, skipping blank lines. A truncated final line
    (build killed mid-write) raises ``ValueError`` naming the line
    number; ``skip_invalid=True`` drops unparseable lines instead and
    keeps the valid ones — the salvage mode ``makisu-tpu report`` uses
    on logs of killed builds."""
    out: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, start=1):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError as e:
                if skip_invalid:
                    continue
                raise ValueError(
                    f"{path}:{i}: invalid event JSON: {e}") from e
    return out
