"""Build event bus: the durable, streamable record of one build.

Spans (utils/metrics.py) answer "how long"; events answer "what
happened, in order, with what identity" — and unlike the span tree,
which only materializes when the build ends, events leave the process
the moment they occur. Three consumers:

- ``--events-out FILE``: a per-build JSONL event log (one JSON object
  per line), written through :class:`JsonlWriter`.
- The worker's ``/build`` response stream: each event rides as its own
  NDJSON frame (``{"event": {...}}``), interleaved with log-line
  frames, so a client watches a build's structure live.
- Tests/tools: any callable sink.

Scoping mirrors the per-build log sink in ``utils/logging.py`` and the
metrics contextvar: sinks bind to the current context, threads a build
spawns inherit them via ``contextvars.copy_context``, and concurrent
worker builds never see each other's events. With no sink bound,
``emit`` is a tuple-read no-op — instrumentation sites pay nothing.

Event shape: ``{"ts": <unix seconds>, "type": <str>, ...fields}``.
Types emitted today: ``build_start``/``build_end`` (cli.py),
``span_start``/``span_end`` (metrics.span), ``step`` (builder/stage.py,
``phase=start|done``), ``cache`` (cache/manager.py + cache/chunks.py,
``result=hit|miss|empty``), ``cache_decision`` (utils/ledger.py — the
cache-decision ledger's structured consult record), ``chunk_fetch``
(cache/chunks.py), and ``registry_blob`` (registry/client.py). The set
is open: any module may emit new types; consumers must ignore types
they don't know.

Like the rest of the telemetry layer: stdlib-only, import-cycle-free,
and never able to fail a build — a raising sink is swallowed (and
counted in ``makisu_events_dropped_total``, so a lossy event log is
detectable instead of silently incomplete).

Beyond the context-scoped sinks, two process-wide facilities ride on
``emit``:

- **global sinks** (:func:`add_global_sink`) see every context's
  events — the worker's process-level flight recorder uses this to
  keep a last-N ring across all builds it serves.
- **the progress clock**: every ``emit`` stamps a monotonic timestamp
  (:func:`last_emit_monotonic`) even when no sink is bound — one float
  store, the cheapest possible liveness signal. The stall watchdog
  (``utils/flightrecorder.py``) and the worker's ``/healthz``
  ``last_progress_seconds`` read it.
"""

from __future__ import annotations

import contextvars
import json
import threading
import time
from typing import Any, Callable

EventSink = Callable[[dict], None]

_sinks: "contextvars.ContextVar[tuple[EventSink, ...]]" = \
    contextvars.ContextVar("makisu_event_sinks", default=())

# Process-wide sinks (worker flight recorder); mutated rarely, read on
# every emit. Kept as a tuple swapped whole so readers never see a
# half-updated list.
_global_sinks: tuple[EventSink, ...] = ()
_global_sinks_lock = threading.Lock()

# Monotonic timestamp of the last emit — the event bus's half of the
# build-progress clock (the transfer engine keeps the other half).
_last_emit = time.monotonic()


def last_emit_monotonic() -> float:
    """``time.monotonic()`` of the most recent :func:`emit` call (any
    context, sink bound or not) — or of an explicit
    :func:`note_progress` (the log path stamps it, so a build that
    logs without emitting events still reads as alive)."""
    return _last_emit


# Contexts whose activity must NOT count as build progress: the stall
# watchdog's own `stall` emit and warning log would otherwise reset the
# very clock it watches — one wedge would re-fire every window and
# /healthz's last_progress_seconds could never exceed it.
_suppress_progress: "contextvars.ContextVar[bool]" = \
    contextvars.ContextVar("makisu_suppress_progress", default=False)


def suppress_progress_stamps():
    """Mark the current context (typically a forensics thread's copied
    context) as not-progress. Returns a reset token."""
    return _suppress_progress.set(True)


# Per-build progress cell: a one-element [monotonic] list bound in the
# build's context. copy_context shares the SAME list with every thread
# the build spawns, so all of a build's activity stamps one cell — and
# a per-build stall watchdog reads THAT cell instead of the process
# clock, which sibling builds in a worker keep fresh (a wedged build
# must not be masked by a healthy neighbor's progress).
_progress_cell: "contextvars.ContextVar[list[float] | None]" = \
    contextvars.ContextVar("makisu_progress_cell", default=None)


def bind_progress_cell():
    """Bind a fresh per-build progress cell in the current context
    (``cli.main`` does this before spawning any build thread).
    Returns a reset token."""
    return _progress_cell.set([time.monotonic()])


def reset_progress_cell(token) -> None:
    _progress_cell.reset(token)


def progress_cell() -> list[float] | None:
    """The context's progress cell, if one is bound."""
    return _progress_cell.get()


def note_progress() -> None:
    """Stamp the progress clock(s) without emitting an event. Two
    float stores — cheap enough for any hot path that proves
    liveness."""
    if _suppress_progress.get():
        return
    global _last_emit
    _last_emit = time.monotonic()
    cell = _progress_cell.get()
    if cell is not None:
        cell[0] = _last_emit


def add_global_sink(sink: EventSink) -> None:
    """Register a process-wide sink that sees every context's events.
    Unlike context sinks this is not scoped — use for process-level
    consumers (the worker's flight recorder), and remove symmetrically
    with :func:`remove_global_sink`."""
    global _global_sinks
    with _global_sinks_lock:
        _global_sinks = _global_sinks + (sink,)


def remove_global_sink(sink: EventSink) -> None:
    global _global_sinks
    with _global_sinks_lock:
        # Equality, not identity: bound methods are recreated per
        # attribute access, and two equal bound methods name one sink.
        _global_sinks = tuple(s for s in _global_sinks if s != sink)


def add_sink(sink: EventSink):
    """Bind an event sink in the current context (stacking on any
    already bound). Returns a token for :func:`reset_sink`."""
    return _sinks.set(_sinks.get() + (sink,))


def reset_sink(token) -> None:
    _sinks.reset(token)


def active() -> bool:
    """Whether any sink (context or global) would receive an emit
    (lets callers skip building expensive event payloads)."""
    return bool(_sinks.get() or _global_sinks)


def emit(event_type: str, **fields: Any) -> None:
    """Deliver one event to every bound sink. No sink: free no-op
    (plus one float store for the progress clock). A sink that raises
    is ignored — events must never fail a build — but the drop is
    counted so consumers can tell their log is incomplete."""
    note_progress()
    sinks = _sinks.get() + _global_sinks
    if not sinks:
        return
    event: dict[str, Any] = {"ts": round(time.time(), 6),
                             "type": event_type}
    event.update(fields)
    _fan_out(event, sinks)


def deliver(event: dict) -> None:
    """Deliver a PRE-FORMED event (already carrying its own ``ts`` and
    ``type``) to every bound sink — the fleet front door uses this to
    tee a worker's streamed build events into its own event log
    without re-stamping them as if they happened here. Same progress
    stamp and swallow-and-count semantics as :func:`emit`."""
    note_progress()
    sinks = _sinks.get() + _global_sinks
    if sinks:
        _fan_out(event, sinks)


def _fan_out(event: dict, sinks: tuple[EventSink, ...]) -> None:
    for sink in sinks:
        try:
            sink(event)
        except Exception:  # noqa: BLE001 - a dead sink must not kill a build
            # Lazy import: metrics imports this module at its top.
            try:
                from makisu_tpu.utils import metrics
                metrics.counter_add("makisu_events_dropped_total",
                                    event_type=event.get("type", "?"))
            except Exception:  # noqa: BLE001 - never recurse into failure
                pass


def promote_context_sinks() -> tuple[EventSink, ...]:
    """Re-register the current context's sinks as PROCESS-WIDE sinks
    and return them (for symmetric :func:`demote_sinks`). The fleet
    front door uses this: ``cli.main`` binds ``--events-out`` /
    ``--explain-out`` writers in the invocation's context, but the
    server's handler and poll threads have no bound context — without
    promotion, every front-door decision and span would silently miss
    the files the operator asked for."""
    sinks = _sinks.get()
    for sink in sinks:
        add_global_sink(sink)
    return sinks


def demote_sinks(sinks: tuple[EventSink, ...]) -> None:
    for sink in sinks:
        remove_global_sink(sink)


class JsonlWriter:
    """Append-only JSONL event sink (the ``--events-out`` file).

    Each event is one line, written and flushed under a lock so the
    concurrent writers a build spawns (cache pushes, chunk uploads,
    shell drains) can't interleave partial lines — a killed build
    leaves at worst one truncated FINAL line, and every line before it
    stays valid JSON.

    ``event_types`` optionally restricts the file to a set of event
    types — how the SLO smoke scenario writes an alert-only NDJSON
    artifact off the same bus the full event log rides."""

    def __init__(self, path: str,
                 event_types: "set[str] | None" = None) -> None:
        self.path = path
        self.event_types = (set(event_types)
                            if event_types is not None else None)
        self._f = open(path, "w", encoding="utf-8")
        self._lock = threading.Lock()
        self._closed = False

    def __call__(self, event: dict) -> None:
        if self.event_types is not None \
                and event.get("type") not in self.event_types:
            return
        line = json.dumps(event, separators=(",", ":"), default=str)
        with self._lock:
            if self._closed:
                return
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                self._f.close()


def read_jsonl(path: str, skip_invalid: bool = False) -> list[dict]:
    """Load an event log, skipping blank lines. A truncated final line
    (build killed mid-write) raises ``ValueError`` naming the line
    number; ``skip_invalid=True`` drops unparseable lines instead and
    keeps the valid ones — the salvage mode ``makisu-tpu report`` uses
    on logs of killed builds."""
    out: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, start=1):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError as e:
                if skip_invalid:
                    continue
                raise ValueError(
                    f"{path}:{i}: invalid event JSON: {e}") from e
    return out
