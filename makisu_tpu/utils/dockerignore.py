""".dockerignore support (capability beyond the reference, which only
offers --blacklist): the build context's `.dockerignore` filters what
ADD/COPY can see, with docker's semantics — last matching pattern wins,
`!` re-includes, `*`/`?` stay inside one path segment, `**` crosses
segments, a pattern matching a directory excludes everything beneath it
(moby/patternmatcher behavior).

Integration model: patterns are evaluated once per build against a walk
of the context, producing a MINIMAL set of excluded absolute paths
(a fully-excluded directory contributes one entry, not its subtree) that
merges into the existing copy blacklist — the one prefix-exclusion
mechanism both the on-disk Copier and the MemFS copy-op diff already
honor. Negations are exact: a dir with re-included descendants is
descended into and only its excluded children listed.
"""

from __future__ import annotations

import os
import re

IGNORE_FILE = ".dockerignore"


def _translate_segment(seg: str) -> str:
    """One path segment of a pattern → regex (never crosses '/')."""
    out = []
    i = 0
    while i < len(seg):
        c = seg[i]
        if c == "*":
            out.append("[^/]*")
        elif c == "?":
            out.append("[^/]")
        elif c == "[":
            j = i + 1
            if j < len(seg) and seg[j] in ("!", "^"):
                j += 1
            if j < len(seg) and seg[j] == "]":
                j += 1
            while j < len(seg) and seg[j] != "]":
                j += 1
            if j < len(seg):  # a real character class
                cls = seg[i + 1:j]
                if cls.startswith("!"):
                    cls = "^" + cls[1:]
                out.append("[" + cls + "]")
                i = j
            else:
                out.append(re.escape(c))
        else:
            out.append(re.escape(c))
        i += 1
    return "".join(out)


def _translate(pattern: str) -> re.Pattern:
    segs = pattern.split("/")
    parts: list[str] = []
    for idx, seg in enumerate(segs):
        last = idx == len(segs) - 1
        if seg == "**":
            # "a/**/b": zero or more whole segments; trailing "a/**"
            # matches everything beneath a (but not a itself).
            parts.append(".*" if last else "(?:[^/]+/)*")
        else:
            parts.append(_translate_segment(seg) + ("" if last else "/"))
    return re.compile("".join(parts) + r"\Z")


class PrefixSet:
    """Sorted prefix set with O(log n) descendant lookup — the minimal
    excluded set can be large when negations force per-file entries
    (e.g. 20k-file node_modules with one re-inclusion), and the
    checksum walk probes it once per context path. Entries must be
    prefix-free (no entry beneath another), which excluded_paths'
    collapse guarantees."""

    def __init__(self, paths: list[str]) -> None:
        import bisect
        self._bisect = bisect.bisect_right
        self._sorted = sorted(p.rstrip("/") for p in paths)

    def __bool__(self) -> bool:
        return bool(self._sorted)

    def covers(self, path: str) -> bool:
        """True if path equals or sits beneath any entry."""
        if not self._sorted:
            return False
        path = path.rstrip("/")
        i = self._bisect(self._sorted, path)
        if i and self._sorted[i - 1] == path:
            return True
        # The nearest entry <= path is the only possible ancestor (the
        # set is prefix-free and sorted).
        return bool(i) and path.startswith(self._sorted[i - 1] + "/")


class DockerIgnore:
    """Parsed .dockerignore: ordered (negated, regex) rules."""

    def __init__(self, lines: list[str]) -> None:
        self.rules: list[tuple[bool, re.Pattern]] = []
        self.has_negations = False
        for raw in lines:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            negated = line.startswith("!")
            if negated:
                line = line[1:].strip()
            # Normalize like docker: patterns are context-root-relative.
            line = line.lstrip("/").rstrip("/")
            line = os.path.normpath(line) if line else ""
            if not line or line == ".":
                continue
            self.rules.append((negated, _translate(line)))
            if negated:
                self.has_negations = True

    @classmethod
    def load(cls, context_dir: str) -> "DockerIgnore | None":
        path = os.path.join(context_dir, IGNORE_FILE)
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                ignore = cls(f.read().splitlines())
        except OSError:
            return None
        return ignore if ignore.rules else None

    def excluded(self, rel: str) -> bool:
        """Docker's algorithm: walk rules in order; a rule matching the
        path OR any ancestor sets the current verdict (last wins)."""
        candidates = [rel]
        parent = os.path.dirname(rel)
        while parent:
            candidates.append(parent)
            parent = os.path.dirname(parent)
        verdict = False
        for negated, rx in self.rules:
            if any(rx.match(c) for c in candidates):
                verdict = not negated
        return verdict

    def excluded_paths(self, context_dir: str) -> list[str]:
        """Walk the context ONCE; return the minimal excluded
        absolute-path set. Without negations an excluded directory is
        pruned whole (nothing beneath can be re-included); with
        negations excluded dirs recurse and collapse back to one entry
        only when every descendant — files, symlinks, and empty dirs
        alike — stayed excluded."""
        return self._walk(context_dir, "")[1]

    def _walk(self, dir_abs: str, dir_rel: str) -> tuple[bool, list[str]]:
        """Returns (all_excluded, minimal_entries) for the contents of
        ``dir_abs``: all_excluded means every entry beneath it is
        excluded (vacuously true for an empty dir); minimal_entries is
        the collapsed excluded set beneath it (never the dir itself)."""
        try:
            names = sorted(os.listdir(dir_abs))
        except OSError:
            return False, []  # unreadable: claim nothing
        all_excluded = True
        entries: list[str] = []
        for name in names:
            abs_path = os.path.join(dir_abs, name)
            rel = os.path.join(dir_rel, name) if dir_rel else name
            is_dir = os.path.isdir(abs_path) and \
                not os.path.islink(abs_path)
            child_excluded = self.excluded(rel)
            if child_excluded and (not is_dir or not self.has_negations):
                entries.append(abs_path)  # whole subtree prunes
                continue
            if not is_dir:
                all_excluded = False
                continue
            sub_all, sub_entries = self._walk(abs_path, rel)
            if child_excluded and sub_all:
                entries.append(abs_path)  # collapse to one entry
            else:
                # Child survives (not excluded, or a descendant was
                # re-included): carry its excluded descendants only.
                all_excluded = False
                entries.extend(sub_entries)
        return all_excluded, entries
