"""Structured logging: JSON (default, k8s-friendly) or console encoding.

Reference: lib/log (zap singleton) + bin/makisu/cmd/common.go:46-66.
"""

from __future__ import annotations

import contextvars
import json
import logging
import sys
import time
from typing import Any, Callable

from makisu_tpu.utils import events

_LOGGER_NAME = "makisu"

# Per-build log sink (worker mode): each /build request binds its own
# sink in its context; threads a build spawns (shell output drains,
# async cache pushes) carry the context along, so concurrent builds'
# log streams never cross. A plain logging.Handler on the shared logger
# could not do this — every handler sees every build's records.
_build_sink: "contextvars.ContextVar[tuple[Callable, int] | None]" = \
    contextvars.ContextVar("makisu_build_sink", default=None)


def set_build_sink(sink: "Callable[[str, str, dict], None] | None",
                   level: str = "info"):
    """Bind a per-context sink receiving (level, message, fields) for
    records at or above ``level``. Returns a token for
    reset_build_sink."""
    threshold = getattr(logging, level.upper(), logging.INFO)
    return _build_sink.set(None if sink is None else (sink, threshold))


def reset_build_sink(token) -> None:
    _build_sink.reset(token)


# Context-scoped log taps: lightweight observers receiving EVERY record
# regardless of level, stacking like the event-bus sinks. The flight
# recorder (utils/flightrecorder.py) binds one per build so diagnostic
# bundles carry the last-N log records. Unlike the build sink, taps are
# many and level-blind — a ring buffer wants debug lines too.
_taps: "contextvars.ContextVar[tuple[Callable, ...]]" = \
    contextvars.ContextVar("makisu_log_taps", default=())


def add_tap(tap: "Callable[[str, str, dict], None]"):
    """Bind a (level, message, fields) observer in the current context,
    stacking on any already bound. Returns a token for
    :func:`reset_tap`."""
    return _taps.set(_taps.get() + (tap,))


def reset_tap(token) -> None:
    _taps.reset(token)


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out: dict[str, Any] = {
            "level": record.levelname.lower(),
            # The record's own creation time, NOT format time: records
            # drained late (handler contention, worker stream backlog)
            # must carry the moment they were emitted.
            "ts": round(record.created, 6),
            "msg": record.getMessage(),
        }
        extra = getattr(record, "fields", None)
        if extra:
            out.update(extra)
        if record.exc_info and record.exc_info[0]:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


class _ConsoleFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        msg = f"{ts} {record.levelname:<5} {record.getMessage()}"
        extra = getattr(record, "fields", None)
        if extra:
            kv = " ".join(f"{k}={v}" for k, v in extra.items())
            msg = f"{msg}  {kv}"
        if record.exc_info and record.exc_info[0]:
            msg += "\n" + self.formatException(record.exc_info)
        return msg


_configure_lock = __import__("threading").Lock()
_configured_as: tuple | None = None


def configure(level: str = "info", fmt: str = "json",
              output: str = "stdout") -> None:
    """(Re)configure the shared logger. Serialized, idempotent for
    unchanged settings, and the handler list is swapped by a SINGLE
    assignment — an emitter mid-callHandlers keeps iterating the old
    list, so concurrent worker builds never drop records during a
    reconfigure. With DIFFERENT settings the last caller wins for the
    shared console stream; per-build log levels apply to build sinks,
    not here."""
    global _configured_as
    with _configure_lock:
        if _configured_as == (level, fmt, output):
            return
        logger = logging.getLogger(_LOGGER_NAME)
        stream = sys.stderr if output == "stderr" else sys.stdout
        handler = (logging.FileHandler(output) if output not in
                   ("stdout", "stderr") else logging.StreamHandler(stream))
        handler.setFormatter(_JsonFormatter() if fmt == "json"
                             else _ConsoleFormatter())
        logger.handlers = [handler]  # atomic swap, no clear/add window
        logger.setLevel(getattr(logging, level.upper(), logging.INFO))
        logger.propagate = False
        _configured_as = (level, fmt, output)


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        configure(fmt="console")
    return logger


def _log(level: int, msg: str, *args: Any, **fields: Any) -> None:
    if args:
        msg = msg % args
    # A log line proves the process is alive: stamp the progress clock
    # so a build that logs (a long RUN step draining output) without
    # emitting bus events doesn't read as stalled to the watchdog.
    events.note_progress()
    get_logger().log(level, msg, extra={"fields": fields} if fields else {})
    for tap in _taps.get():
        try:
            tap(logging.getLevelName(level).lower(), msg, fields)
        except Exception:  # noqa: BLE001 - a dead tap must not kill logging
            pass
    bound = _build_sink.get()
    if bound is not None:
        sink, threshold = bound
        if level < threshold:
            return
        try:
            sink(logging.getLevelName(level).lower(), msg, fields)
        except Exception:  # noqa: BLE001 - a dead client must not kill logging
            pass


def debug(msg: str, *args: Any, **fields: Any) -> None:
    _log(logging.DEBUG, msg, *args, **fields)


def info(msg: str, *args: Any, **fields: Any) -> None:
    _log(logging.INFO, msg, *args, **fields)


def warning(msg: str, *args: Any, **fields: Any) -> None:
    _log(logging.WARNING, msg, *args, **fields)


def error(msg: str, *args: Any, **fields: Any) -> None:
    _log(logging.ERROR, msg, *args, **fields)
