"""Continuous wall-clock sampling profiler: where the time actually went.

Every attribution layer so far works from *declared* timing — spans
the code chose to open, stage busy-counters the commit pipeline chose
to bump. The gap: when `history diff` or an SLO burn alert says a
build got slower, nothing names the *frames* responsible, and the
~1.15s warm-resident floor is opaque below the span level. This module
is the attribution tool:

- :class:`SamplingProfiler` — a daemon thread walking
  ``sys._current_frames()`` at ``--profile-hz`` (default ~67 Hz,
  ``MAKISU_TPU_PROFILE_HZ``, 0 = off), folding each working thread's
  stack into bounded collapsed-stack counts tagged with the owning
  build's trace id and current phase (joined through the open-span
  plane + ``traceexport.phase_of``). Parked stdlib threads (pool
  workers idling in ``threading.py`` waits) and the forensics layer's
  own threads are excluded — the same representative-frame discipline
  the device-probe watcher uses.
- Self-measured overhead: every sampling pass is timed, the cumulative
  cost over wall time is exported (``makisu_profiler_overhead_ratio``)
  and governed — when a pass costs more than the budget (default 2%)
  allows at the configured rate, the sampler stretches its sleep
  instead of lying about its cost.
- ``makisu-tpu.profile.v1`` artifacts: folded stacks plus an embedded
  speedscope-compatible sampled profile (drop into speedscope.app),
  written with ``--profile-out``, ``SIGUSR2``, the worker's
  ``GET /profile?seconds=N``, and the fleet front door's merged
  cross-worker aggregation.
- :func:`diff` — differential profiles: which frames' self-time SHARE
  grew between two artifacts (the question behind every latency
  regression), with the `history diff` exit-code contract (1 = flagged).

Like the rest of the telemetry layer: stdlib-only, lock-free where a
signal handler can reach it (snapshot reads are retry-reads of
GIL-atomic dicts), and never able to fail a build.
"""

from __future__ import annotations

import html as html_mod
import os
import sys
import threading
import time
from typing import Any, Iterable

from makisu_tpu.utils import events, logging as log, metrics

PROFILE_SCHEMA = "makisu-tpu.profile.v1"

# ~67 Hz: prime-ish and off the 10ms/100ms beat of most sleep loops,
# so periodic work can't hide between samples (lockstep aliasing).
DEFAULT_HZ = 67.0

# Bounded memory: distinct folded-stack keys per profile. Stack-shape
# churn past the cap increments `dropped` instead of growing the dict.
DEFAULT_MAX_STACKS = 8192

# Distinct trace ids tallied before new ones collapse into "" — a
# long-lived worker mints one per build and must not grow unbounded.
_MAX_TRACES = 256

# Self-imposed overhead ceiling: the fraction of wall time the sampler
# may spend sampling before it stretches its own interval.
DEFAULT_BUDGET = 0.02

_STACK_DEPTH = 48

# Frames that are the interpreter's parking lot, not a location —
# Event/Condition waits, queue gets, selector polls, the pool-worker
# dispatch loop. A thread whose innermost frames are all parking is
# trimmed down to its first real frame (the representative-frame
# discipline from ops/backend.py); a thread that is NOTHING but
# parking frames is an idle pool/server thread and contributes no
# samples. Build threads blocked inside these waits still count —
# trimmed to the project frame doing the waiting — because wall-clock
# time spent blocked IS build latency.
_PARKING_FILES = ("threading.py", "queue.py", "selectors.py",
                  "socketserver.py", "thread.py")
_SELF_FILES = ("profiler.py",)

# Threads that exist BECAUSE of the telemetry/forensics layer: never
# build work, never sampled.
_FORENSIC_THREADS = ("profiler-sampler", "stall-watchdog",
                     "resource-sampler", "slo-evaluator",
                     "canary-driver")


def resolve_hz(flag: float | None = None) -> float:
    """The sampling rate this process should run: an explicit
    ``--profile-hz`` wins, else ``MAKISU_TPU_PROFILE_HZ``, else the
    always-on default. 0 (or garbage) anywhere in the chain = off."""
    if flag is not None:
        return max(float(flag), 0.0)
    raw = os.environ.get("MAKISU_TPU_PROFILE_HZ", "")
    if raw:
        try:
            return max(float(raw), 0.0)
        except ValueError:
            return 0.0
    return DEFAULT_HZ


# -- thread → trace binding --------------------------------------------------

# Which build each thread is working for: cli.main binds its invocation
# thread to its registry's trace id, so a worker running N concurrent
# builds attributes each handler thread's samples to the right build.
# Unbound threads (pipeline pool workers) fall back to the sole active
# trace when only one build is in flight, else to stack-shape phase
# inference. GIL-atomic dict ops only — the sampler reads it lock-free.
_thread_traces: dict[int, str] = {}


def bind_thread(trace_id: str):
    """Tag the CURRENT thread's samples with ``trace_id``. Returns a
    token for :func:`unbind_thread`."""
    ident = threading.get_ident()
    token = (ident, _thread_traces.get(ident))
    _thread_traces[ident] = trace_id
    return token


def unbind_thread(token) -> None:
    ident, prev = token
    if prev is None:
        _thread_traces.pop(ident, None)
    else:
        _thread_traces[ident] = prev


# -- the process profiler registry -------------------------------------------

# One sampler per process: the worker arms it for its lifetime; a
# standalone cli.main arms one per invocation only when no process-
# level sampler already covers it (a build inside a worker must not
# double-sample).
_process_profiler: "SamplingProfiler | None" = None


def set_process_profiler(p: "SamplingProfiler | None") -> None:
    global _process_profiler
    _process_profiler = p


def process_profiler() -> "SamplingProfiler | None":
    return _process_profiler


# -- sampling ----------------------------------------------------------------


def _frame_label(code, lineno: int | None = None) -> str:
    base = os.path.basename(code.co_filename)
    return f"{code.co_name} ({base})"


def _fold_stack(frame) -> tuple[list[str], bool]:
    """Walk one thread's frame chain innermost→outermost into
    root-first labels. Returns ``(labels, working)``: consecutive
    innermost parking frames and the profiler's own frames are
    trimmed, and ``working`` is False when nothing but parking
    plumbing remains — an idle pool/server thread, not build work."""
    inner: list[str] = []
    working = False
    f = frame
    while f is not None and len(inner) < _STACK_DEPTH:
        code = f.f_code
        base = os.path.basename(code.co_filename)
        if not inner and base in _PARKING_FILES + _SELF_FILES:
            f = f.f_back
            continue  # still trimming the parked/self leaf
        inner.append(f"{code.co_name} ({base})")
        if base not in _PARKING_FILES + _SELF_FILES:
            working = True
        f = f.f_back
    inner.reverse()
    return inner, working


def _phase_from_stack(labels: list[str]) -> str:
    """Fallback phase attribution from the stack itself: the innermost
    frame whose name matches a phase rule (commit pipeline workers are
    unbound threads, but their function/file names carry the phase)."""
    from makisu_tpu.utils import traceexport
    for label in reversed(labels):
        phase = traceexport.phase_of(label)
        if phase != "other":
            return phase
    return "other"


def _open_phases() -> dict[str, str]:
    """Current phase per trace id from the open-span plane: the
    LATEST-started open leaf span names where each build is right
    now, mapped through ``traceexport.phase_of``."""
    from makisu_tpu.utils import traceexport
    best: dict[str, tuple[float, str]] = {}
    for span in metrics.open_span_snapshot():
        if not span.get("leaf"):
            continue
        tid = span.get("trace_id") or ""
        start = float(span.get("start") or 0.0)
        if tid not in best or start >= best[tid][0]:
            best[tid] = (start, span.get("name", ""))
    return {tid: traceexport.phase_of(name)
            for tid, (_start, name) in best.items()}


class SamplingProfiler:
    """The always-on wall-clock sampler. ``start`` spawns the daemon
    thread; every read path (``stats``, ``snapshot``, ``window``) is a
    lock-free retry-read, safe from signal handlers and HTTP handler
    threads while sampling continues."""

    def __init__(self, hz: float = DEFAULT_HZ,
                 max_stacks: int = DEFAULT_MAX_STACKS,
                 budget: float = DEFAULT_BUDGET) -> None:
        self.hz = max(float(hz), 0.0)
        self.max_stacks = max(int(max_stacks), 16)
        self.budget = max(float(budget), 0.001)
        # Mutated ONLY by the sampler thread; GIL-atomic ops so readers
        # take consistent-enough snapshots without a lock.
        self._stacks: dict[tuple[str, str], int] = {}
        self._phases: dict[str, int] = {}
        self._traces: dict[str, int] = {}
        self.samples_total = 0
        self.passes = 0
        self.dropped = 0
        self.throttled = 0
        self.cost_seconds = 0.0
        self.started_mono: float | None = None
        self.started_ts: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle --------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.hz > 0 and self._thread is not None

    def start(self) -> "SamplingProfiler":
        if self.hz <= 0 or self._thread is not None:
            return self
        self.started_mono = time.monotonic()
        self.started_ts = time.time()
        # Process-level sampling thread: must not pin any build's
        # registry/log context.  # check: allow(ctx-propagation)
        self._thread = threading.Thread(
            target=self._run, name="profiler-sampler", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # -- the sampling loop ------------------------------------------------

    def _run(self) -> None:
        # The sampler's own activity must not stamp the progress clock
        # the stall watchdog polls — sampling is observation, not work.
        events.suppress_progress_stamps()
        interval = 1.0 / self.hz
        next_metrics = 0.0
        while not self._stop.is_set():
            t0 = time.monotonic()
            # Cost is the sampler thread's own CPU time, not wall
            # time: under GIL contention a pass can WAIT a long time
            # while imposing almost nothing — throttling on wall time
            # would starve the sampler exactly when the process is
            # busiest (the moment profiles matter).
            c0 = time.thread_time()
            try:
                self._sample_once()
            except Exception as e:  # noqa: BLE001 - observation never kills work
                self.dropped += 1
                log.debug("sampler pass failed: %s", e)
            cost = time.thread_time() - c0
            self.cost_seconds += cost
            self.passes += 1
            if t0 >= next_metrics:
                self._export_metrics()
                next_metrics = t0 + 1.0
            # Overhead governor: a pass that cost more than the budget
            # allows at the nominal rate stretches THIS sleep so the
            # cumulative overhead fraction converges under the budget.
            sleep = interval
            floor = cost / self.budget
            if floor > interval:
                sleep = floor
                self.throttled += 1
            self._stop.wait(sleep)

    def _sample_once(self) -> None:
        own = threading.get_ident()
        frames = sys._current_frames()
        forensic = {t.ident for t in threading.enumerate()
                    if t.name in _FORENSIC_THREADS
                    or t.name.startswith("profiler-")}
        phases = _open_phases()
        sole_trace = next(iter(phases)) if len(phases) == 1 else ""
        for ident, frame in frames.items():
            if ident == own or ident in forensic:
                continue
            labels, working = _fold_stack(frame)
            if not labels or not working:
                continue
            trace = _thread_traces.get(ident) or sole_trace
            phase = phases.get(trace) or _phase_from_stack(labels)
            self._count(";".join(labels), phase, trace)

    def _count(self, folded: str, phase: str, trace: str) -> None:
        key = (phase, folded)
        current = self._stacks.get(key)
        if current is None and len(self._stacks) >= self.max_stacks:
            self.dropped += 1
        else:
            self._stacks[key] = (current or 0) + 1
        self._phases[phase] = self._phases.get(phase, 0) + 1
        if trace not in self._traces and len(self._traces) >= _MAX_TRACES:
            trace = ""
        self._traces[trace] = self._traces.get(trace, 0) + 1
        self.samples_total += 1

    def _export_metrics(self) -> None:
        g = metrics.global_registry()
        g.gauge_set(metrics.PROFILER_SAMPLES, self.samples_total)
        g.gauge_set(metrics.PROFILER_DROPPED, self.dropped)
        g.gauge_set(metrics.PROFILER_STACKS, len(self._stacks))
        g.gauge_set(metrics.PROFILER_OVERHEAD, self.overhead_fraction())

    # -- reads ------------------------------------------------------------

    def overhead_fraction(self) -> float:
        if self.started_mono is None:
            return 0.0
        wall = max(time.monotonic() - self.started_mono, 1e-6)
        return min(self.cost_seconds / wall, 1.0)

    def stats(self) -> dict[str, Any]:
        """The worker ``/healthz`` ``profiler`` section: cheap, no
        stack serialization."""
        return {
            "enabled": self.enabled,
            "hz": self.hz,
            "samples_total": self.samples_total,
            "dropped": self.dropped,
            "throttled": self.throttled,
            "distinct_stacks": len(self._stacks),
            "overhead_fraction": round(self.overhead_fraction(), 5),
        }

    def snapshot(self, command: str = "") -> dict[str, Any]:
        """The full ``makisu-tpu.profile.v1`` document (sans the
        embedded speedscope export — :func:`write_artifact` adds it).
        Retry-reads, so callable while sampling continues and from
        signal context."""
        stacks = metrics.snapshot_concurrent(self._stacks.items())
        phases = dict(metrics.snapshot_concurrent(self._phases.items()))
        traces = dict(metrics.snapshot_concurrent(self._traces.items()))
        duration = (time.monotonic() - self.started_mono
                    if self.started_mono is not None else 0.0)
        return {
            "schema": PROFILE_SCHEMA,
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "command": command,
            "hz": self.hz,
            "duration_seconds": round(duration, 3),
            "samples": self.samples_total,
            "passes": self.passes,
            "dropped": self.dropped,
            "throttled": self.throttled,
            "overhead_fraction": round(self.overhead_fraction(), 5),
            "budget_fraction": self.budget,
            "phases": {k: v for k, v in sorted(phases.items())},
            "traces": {k: v for k, v in sorted(traces.items())},
            "stacks": sorted(
                ({"stack": folded, "phase": phase, "count": count}
                 for (phase, folded), count in stacks),
                key=lambda row: -row["count"]),
        }

    def window(self, seconds: float, command: str = "") -> dict[str, Any]:
        """An on-demand capture window (the worker's ``GET
        /profile?seconds=N``): the DELTA between two snapshots, so a
        long-lived process answers "what is it doing right now" rather
        than "what has it ever done". Blocks the calling thread for
        ``seconds``; sampling continues underneath."""
        before = self.snapshot(command)
        self._stop.wait(min(max(float(seconds), 0.1), 60.0))
        after = self.snapshot(command)
        return subtract(after, before)


# -- document algebra --------------------------------------------------------


def subtract(after: dict, before: dict) -> dict:
    """``after - before`` for two snapshots of ONE profiler: counts
    subtract, identity fields come from ``after``."""
    prior = {(row["phase"], row["stack"]): row["count"]
             for row in before.get("stacks") or []}
    stacks = []
    for row in after.get("stacks") or []:
        count = row["count"] - prior.get((row["phase"], row["stack"]), 0)
        if count > 0:
            stacks.append({"stack": row["stack"], "phase": row["phase"],
                           "count": count})
    out = dict(after)
    out["stacks"] = sorted(stacks, key=lambda r: -r["count"])
    out["samples"] = max(after.get("samples", 0)
                         - before.get("samples", 0), 0)
    out["passes"] = max(after.get("passes", 0)
                        - before.get("passes", 0), 0)
    out["dropped"] = max(after.get("dropped", 0)
                         - before.get("dropped", 0), 0)
    out["duration_seconds"] = round(max(
        after.get("duration_seconds", 0.0)
        - before.get("duration_seconds", 0.0), 0.0), 3)
    for field in ("phases", "traces"):
        prior_map = before.get(field) or {}
        merged = {}
        for key, value in (after.get(field) or {}).items():
            delta = value - prior_map.get(key, 0)
            if delta > 0:
                merged[key] = delta
        out[field] = merged
    return out


def merge_profiles(docs: dict[str, dict]) -> dict:
    """Fleet aggregation: merge per-worker profile documents into one
    (stack counts sum; per-worker vitals kept in ``workers``)."""
    stacks: dict[tuple[str, str], int] = {}
    phases: dict[str, int] = {}
    traces: dict[str, int] = {}
    workers: dict[str, dict] = {}
    samples = dropped = 0
    duration = 0.0
    hz = 0.0
    for worker_id, doc in sorted(docs.items()):
        for row in doc.get("stacks") or []:
            key = (row.get("phase", "other"), row.get("stack", ""))
            stacks[key] = stacks.get(key, 0) + int(row.get("count", 0))
        for phase, count in (doc.get("phases") or {}).items():
            phases[phase] = phases.get(phase, 0) + int(count)
        for tid, count in (doc.get("traces") or {}).items():
            traces[tid] = traces.get(tid, 0) + int(count)
        samples += int(doc.get("samples", 0))
        dropped += int(doc.get("dropped", 0))
        duration = max(duration, float(doc.get("duration_seconds", 0.0)))
        hz = max(hz, float(doc.get("hz", 0.0)))
        workers[worker_id] = {
            "samples": int(doc.get("samples", 0)),
            "hz": float(doc.get("hz", 0.0)),
            "overhead_fraction": float(doc.get("overhead_fraction",
                                               0.0)),
            "dropped": int(doc.get("dropped", 0)),
        }
    rows = sorted(({"stack": folded, "phase": phase, "count": count}
                   for (phase, folded), count in stacks.items()),
                  key=lambda r: -r["count"])
    if len(rows) > DEFAULT_MAX_STACKS:
        dropped += sum(r["count"] for r in rows[DEFAULT_MAX_STACKS:])
        rows = rows[:DEFAULT_MAX_STACKS]
    return {
        "schema": PROFILE_SCHEMA,
        "ts": round(time.time(), 3),
        "pid": 0,
        "command": "fleet",
        "hz": hz,
        "duration_seconds": round(duration, 3),
        "samples": samples,
        "dropped": dropped,
        "overhead_fraction": max(
            (w["overhead_fraction"] for w in workers.values()),
            default=0.0),
        "phases": {k: v for k, v in sorted(phases.items())},
        "traces": {k: v for k, v in sorted(traces.items())},
        "stacks": rows,
        "workers": workers,
    }


def self_time_by_frame(doc: dict) -> dict[str, int]:
    """Samples per LEAF frame — the folded stack's innermost entry
    owns the sample (self time), the collapsed-stack convention."""
    out: dict[str, int] = {}
    for row in doc.get("stacks") or []:
        frames = row.get("stack", "").split(";")
        if not frames or not frames[-1]:
            continue
        out[frames[-1]] = out.get(frames[-1], 0) + int(row.get("count",
                                                               0))
    return out


def frames_by_phase(doc: dict) -> dict[str, dict[str, int]]:
    """Self-time frames bucketed by attributed phase."""
    out: dict[str, dict[str, int]] = {}
    for row in doc.get("stacks") or []:
        frames = row.get("stack", "").split(";")
        if not frames or not frames[-1]:
            continue
        bucket = out.setdefault(row.get("phase", "other"), {})
        bucket[frames[-1]] = bucket.get(frames[-1], 0) \
            + int(row.get("count", 0))
    return out


def dominant_frame(doc: dict, phase: str) -> tuple[str, int] | None:
    """The hottest self-time frame of one phase — what `doctor` names
    when a phase is slow."""
    bucket = frames_by_phase(doc).get(phase) or {}
    if not bucket:
        return None
    frame = max(sorted(bucket), key=lambda f: bucket[f])
    return frame, bucket[frame]


# -- artifacts ---------------------------------------------------------------


def speedscope_profile(doc: dict) -> dict:
    """A speedscope-compatible sampled profile of the folded stacks
    (one synthetic sample per count unit; weights carry the counts so
    the file stays small)."""
    frame_index: dict[str, int] = {}
    frames: list[dict] = []
    samples: list[list[int]] = []
    weights: list[int] = []
    for row in doc.get("stacks") or []:
        stack = []
        for label in row.get("stack", "").split(";"):
            if label not in frame_index:
                frame_index[label] = len(frames)
                frames.append({"name": label})
            stack.append(frame_index[label])
        samples.append(stack)
        weights.append(int(row.get("count", 0)))
    total = sum(weights)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [{
            "type": "sampled",
            "name": f"makisu-tpu {doc.get('command', '')} "
                    f"pid {doc.get('pid', '?')}".strip(),
            "unit": "none",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        }],
        "name": "makisu-tpu profile",
        "activeProfileIndex": 0,
        "exporter": "makisu-tpu",
    }


def write_artifact(path: str, doc: dict) -> str:
    """Write the profile artifact (folded stacks + embedded speedscope
    export) atomically."""
    out = dict(doc)
    out["speedscope"] = speedscope_profile(doc)
    metrics.write_json_atomic(path, out)
    return path


def read_artifact(path: str) -> dict:
    """Load and validate a profile artifact. Raises ``ValueError`` on
    unreadable/wrong-schema input (the CLI maps it to exit 2, the
    `history diff` unreadable-input contract)."""
    import json
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as exc:
        raise ValueError(f"unreadable profile {path}: {exc}") from exc
    if not isinstance(doc, dict) \
            or doc.get("schema") != PROFILE_SCHEMA:
        raise ValueError(
            f"{path} is not a {PROFILE_SCHEMA} artifact "
            f"(schema: {doc.get('schema') if isinstance(doc, dict) else '?'})")
    return doc


# -- differential profiles ---------------------------------------------------


def diff(baseline: dict, candidate: dict,
         threshold: float = 0.1) -> dict:
    """Attribute a regression to frames: for every frame, compare its
    self-time SHARE of total samples between the two profiles and flag
    growth beyond ``threshold`` (absolute share points as a fraction —
    0.1 flags a frame that grew from 2% to 13% of the build). Shares,
    not counts: the two captures may differ in duration and rate."""
    total_a = max(sum(self_time_by_frame(baseline).values()), 0)
    total_b = max(sum(self_time_by_frame(candidate).values()), 0)
    frames_a = self_time_by_frame(baseline)
    frames_b = self_time_by_frame(candidate)
    if not total_a or not total_b:
        return {"ok": True, "insufficient_samples": True,
                "threshold": threshold, "regressions": [],
                "baseline_samples": total_a,
                "candidate_samples": total_b, "phases": []}
    regressions: list[dict] = []
    for frame in sorted(set(frames_a) | set(frames_b)):
        share_a = frames_a.get(frame, 0) / total_a
        share_b = frames_b.get(frame, 0) / total_b
        growth = share_b - share_a
        if growth > threshold:
            regressions.append({
                "frame": frame,
                "baseline_share": round(share_a, 4),
                "candidate_share": round(share_b, 4),
                "growth": round(growth, 4),
            })
    regressions.sort(key=lambda r: -r["growth"])
    phase_rows: list[dict] = []
    pa = baseline.get("phases") or {}
    pb = candidate.get("phases") or {}
    sum_a = max(sum(pa.values()), 1)
    sum_b = max(sum(pb.values()), 1)
    for phase in sorted(set(pa) | set(pb)):
        phase_rows.append({
            "phase": phase,
            "baseline_share": round(pa.get(phase, 0) / sum_a, 4),
            "candidate_share": round(pb.get(phase, 0) / sum_b, 4),
        })
    return {
        "ok": not regressions,
        "threshold": threshold,
        "regressions": regressions,
        "baseline_samples": total_a,
        "candidate_samples": total_b,
        "phases": phase_rows,
    }


def render_diff(result: dict) -> str:
    """The ``makisu-tpu profile diff A B`` output."""
    lines = [
        "profile diff — baseline vs candidate "
        f"(threshold {100.0 * result['threshold']:.0f}% share growth)",
        f"  samples: {result['baseline_samples']} vs "
        f"{result['candidate_samples']}",
    ]
    if result.get("insufficient_samples"):
        lines.append("  one side has no samples — no signal, "
                     "not a regression")
        return "\n".join(lines) + "\n"
    moved = [row for row in result["phases"]
             if abs(row["candidate_share"] - row["baseline_share"])
             >= 0.01]
    for row in moved:
        lines.append(
            f"  phase {row['phase']:<6s} "
            f"{100.0 * row['baseline_share']:5.1f}% → "
            f"{100.0 * row['candidate_share']:5.1f}%")
    lines.append("")
    if result["regressions"]:
        lines.append(f"REGRESSION: {len(result['regressions'])} "
                     f"frame(s) grew beyond the threshold:")
        for r in result["regressions"][:10]:
            lines.append(
                f"  {r['frame']:<44s} "
                f"{100.0 * r['baseline_share']:5.1f}% → "
                f"{100.0 * r['candidate_share']:5.1f}%  "
                f"(+{100.0 * r['growth']:.1f} points)")
    else:
        lines.append("ok: no frame's self-time share grew beyond the "
                     "threshold")
    return "\n".join(lines) + "\n"


# -- renderers ---------------------------------------------------------------


def render_profile(doc: dict, top: int = 10) -> str:
    """The ``makisu-tpu profile ARTIFACT`` output: capture vitals, the
    phase-attributed breakdown, and top self-time frames (overall and
    per phase)."""
    from makisu_tpu.utils import traceexport
    total = max(int(doc.get("samples", 0)), 0)
    lines = [
        f"makisu-tpu profile — {doc.get('command') or '?'}  "
        f"pid {doc.get('pid', '?')}",
        f"captured {doc.get('duration_seconds', 0.0):.1f}s at "
        f"{doc.get('hz', 0.0):g} Hz — {total} samples, "
        f"{len(doc.get('stacks') or [])} distinct stacks, "
        f"{doc.get('dropped', 0)} dropped",
        f"sampler overhead: "
        f"{100.0 * float(doc.get('overhead_fraction', 0.0)):.2f}% "
        f"of wall time (budget "
        f"{100.0 * float(doc.get('budget_fraction', DEFAULT_BUDGET)):.0f}%)",
    ]
    workers = doc.get("workers")
    if workers:
        lines.append(f"merged from {len(workers)} worker(s): " + "  ".join(
            f"{wid}={w['samples']}" for wid, w in sorted(workers.items())))
    phases = doc.get("phases") or {}
    if phases and total:
        lines.append("")
        lines.append("phase breakdown (sample share):")
        duration = float(doc.get("duration_seconds", 0.0))
        for phase in traceexport.PHASES:
            count = phases.get(phase, 0)
            if not count:
                continue
            share = count / total
            bar = "█" * max(int(share * 40), 1)
            est = f"  ~{share * duration:6.2f}s" if duration else ""
            lines.append(f"  {phase:<6s} {100.0 * share:5.1f}% "
                         f"{count:>7d}{est}  {bar}")
    frames = sorted(self_time_by_frame(doc).items(),
                    key=lambda kv: -kv[1])[:top]
    if frames and total:
        lines.append("")
        lines.append(f"top functions by self time (of {total} samples):")
        for frame, count in frames:
            lines.append(f"  {frame:<44s} {count:>7d} "
                         f"{100.0 * count / total:5.1f}%")
    by_phase = frames_by_phase(doc)
    hot = [(phase, sorted(bucket.items(), key=lambda kv: -kv[1])[0])
           for phase, bucket in sorted(by_phase.items()) if bucket]
    if hot and total:
        lines.append("")
        lines.append("dominant frame per phase:")
        for phase, (frame, count) in hot:
            lines.append(f"  {phase:<6s} {frame:<44s} {count:>7d}")
    traces = doc.get("traces") or {}
    named = {t: n for t, n in traces.items() if t}
    if len(named) > 1:
        lines.append("")
        lines.append(f"samples span {len(named)} builds (trace ids): "
                     + "  ".join(f"{t[:8]}={n}" for t, n in sorted(
                         named.items(), key=lambda kv: -kv[1])[:6]))
    return "\n".join(lines) + "\n"


_PHASE_COLORS = {
    "pull": "#4e79a7", "chunk": "#f28e2b", "hash": "#e15759",
    "push": "#76b7b2", "other": "#9c9c9c",
}


def _stack_tree(doc: dict) -> dict:
    root: dict = {"name": "all", "value": 0, "phase": "other",
                  "children": {}}
    for row in doc.get("stacks") or []:
        count = int(row.get("count", 0))
        phase = row.get("phase", "other")
        root["value"] += count
        node = root
        for label in row.get("stack", "").split(";"):
            child = node["children"].get(label)
            if child is None:
                child = {"name": label, "value": 0, "phase": phase,
                         "children": {}}
                node["children"][label] = child
            child["value"] += count
            node = child
    return root


def flamegraph_html(doc: dict, title: str = "") -> str:
    """A self-contained (no external assets) icicle/flamegraph HTML of
    the folded stacks, phase-colored, hover for counts."""
    root = _stack_tree(doc)
    total = max(root["value"], 1)

    def render(node: dict, share: float) -> str:
        pct = 100.0 * node["value"] / total
        color = _PHASE_COLORS.get(node.get("phase", "other"),
                                  "#9c9c9c")
        name = html_mod.escape(node["name"])
        tip = html_mod.escape(
            f"{node['name']} — {node['value']} samples ({pct:.1f}%)")
        kids = sorted(node["children"].values(),
                      key=lambda c: -c["value"])
        inner = "".join(
            render(child, 100.0 * child["value"] / node["value"])
            for child in kids if child["value"] / total >= 0.001)
        return (f'<div class="f" style="width:{share:.3f}%;'
                f'background:{color}" title="{tip}">'
                f'<span>{name}</span>'
                f'<div class="ch">{inner}</div></div>')

    body = render(root, 100.0)
    heading = html_mod.escape(
        title or f"makisu-tpu profile — {doc.get('command') or '?'} "
                 f"({doc.get('samples', 0)} samples)")
    return f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{heading}</title>
<style>
body {{ font: 12px/1.4 system-ui, sans-serif; margin: 12px; }}
h1 {{ font-size: 14px; }}
.f {{ display: inline-block; vertical-align: top; overflow: hidden;
     box-sizing: border-box; border: 1px solid rgba(255,255,255,.6);
     border-radius: 2px; }}
.f > span {{ display: block; padding: 1px 3px; white-space: nowrap;
     overflow: hidden; text-overflow: ellipsis; color: #fff;
     font-size: 11px; }}
.ch {{ white-space: nowrap; width: 100%; }}
.legend span {{ display: inline-block; padding: 1px 8px; margin-right:
     6px; color: #fff; border-radius: 2px; font-size: 11px; }}
</style></head><body>
<h1>{heading}</h1>
<p class="legend">{"".join(
        f'<span style="background:{color}">{phase}</span>'
        for phase, color in _PHASE_COLORS.items())}</p>
<div style="white-space:nowrap">{body}</div>
</body></html>
"""
