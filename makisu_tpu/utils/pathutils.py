"""Path constants and helpers (reference: lib/pathutils/).

The blacklist is the set of host paths never scanned, copied, or committed
into layers — kernel pseudo-filesystems plus files the container runtime
bind-mounts read-only.
"""

from __future__ import annotations

import functools
import os

DEFAULT_STORAGE_DIR = "/makisu-storage"
DEFAULT_INTERNAL_DIR = "/makisu-internal"
CACHE_KV_FILE_NAME = "cache_key_value.json"

DEFAULT_BLACKLIST = [
    DEFAULT_INTERNAL_DIR,
    "/.dockerinit",
    "/dev",
    "/.dockerenv",
    "/dev/console",
    "/dev/pts",
    "/dev/shm",
    "/etc/hosts",
    "/etc/hostname",
    "/etc/mtab",
    "/etc/resolv.conf",
    "/proc",
    "/sys",
]


@functools.lru_cache(maxsize=65536)
def abs_path(p: str) -> str:
    """Normalize to an absolute path with a leading '/'. Does not resolve
    symlinks (layer paths are logical, not host-resolved).

    Memoized: scans normalize the same paths many times over (each
    blacklist entry per visited file, ancestors per descendant); the
    cache turns the string work into a dict hit on the hot loop."""
    p = os.path.normpath("/" + p)
    if p.startswith("//"):  # POSIX normpath preserves a double leading slash
        p = "/" + p.lstrip("/")
    return p


def rel_path(p: str) -> str:
    """Path relative to '/', with no leading slash."""
    return abs_path(p).lstrip("/")


def trim_root(p: str, root: str) -> str:
    """Strip a root prefix, returning an absolute logical path."""
    root = os.path.normpath(root)
    p = os.path.normpath(p)
    if root in ("/", ""):
        return abs_path(p)
    if p == root:
        return "/"
    if p.startswith(root + os.sep):
        return abs_path(p[len(root):])
    raise ValueError(f"{p!r} is not under root {root!r}")


def join_root(root: str, p: str) -> str:
    """Map a logical absolute path into a physical root directory."""
    return os.path.normpath(os.path.join(root, rel_path(p)))


def split_path(p: str) -> list[str]:
    """Path components, no empties: '/a/b/c' -> ['a','b','c']."""
    return [c for c in abs_path(p).split("/") if c]


def is_descendant_of_any(p: str, ancestors: list[str]) -> bool:
    """True if p equals or sits beneath any listed path."""
    p = abs_path(p)
    for a in ancestors:
        a = abs_path(a)
        if p == a or p.startswith(a.rstrip("/") + "/"):
            return True
    return False


def ancestors(p: str) -> list[str]:
    """All proper ancestor directories of p, outermost first ('/a', '/a/b')."""
    parts = split_path(p)
    return ["/" + "/".join(parts[:i]) for i in range(1, len(parts))]
