"""Bounded worker pool and context-propagating parallel map.

Reference capability: lib/concurrency/worker_pool.go (fixed-N goroutine
pool; Do blocks when the queue is full; Stop/Wait join). Python's
ThreadPoolExecutor has an unbounded queue, which for layer transfers
means unbounded memory; this pool applies backpressure instead.
"""

from __future__ import annotations

import contextvars
import os
import queue
import threading
from typing import Any, Callable, Iterable

# -- layer-commit pipeline workers ----------------------------------------
#
# One knob governs every stage of the multicore commit pipeline (file
# read-ahead, pooled chunk SHA-256, parallel gear block scans):
# ``--hash-workers`` / MAKISU_TPU_HASH_WORKERS, default ``min(8, cpu)``.
# ``1`` restores the fully serial single-thread pipeline. The setting is
# context-scoped (like the build's metrics registry) so concurrent
# worker builds can carry different flags.

_hash_workers_override: "contextvars.ContextVar[int | None]" = \
    contextvars.ContextVar("makisu_hash_workers", default=None)


def default_hash_workers() -> int:
    """``min(8, cpu)``, except hosts under 4 cores default to the
    serial pipeline: the producer thread alone is ~2/3 of the stream
    work, so with fewer than ~3 worker cores the pooled stages' GIL
    handoffs cost more than the overlap wins (measured 0.8x on a
    2-core host). An explicit flag/env still forces pooling there."""
    cpu = os.cpu_count() or 1
    return 1 if cpu < 4 else min(8, cpu)


def hash_workers() -> int:
    """Effective commit-pipeline worker count for this context."""
    override = _hash_workers_override.get()
    if override is not None:
        return max(1, override)
    env = os.environ.get("MAKISU_TPU_HASH_WORKERS", "")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass  # config never fails a build
    return default_hash_workers()


def set_hash_workers(n: int | None):
    """Bind a per-context worker count (the CLI flag). Returns a token
    for :func:`reset_hash_workers`."""
    return _hash_workers_override.set(n)


def reset_hash_workers(token) -> None:
    _hash_workers_override.reset(token)


# -- layer-commit compression workers --------------------------------------
#
# The block-parallel compress stage (tario.BlockGzipWriter) has its own
# knob, separate from --hash-workers: deflate runs entirely in C with
# the GIL released, so it scales on hosts where the GIL-bound pipeline
# stages do not (the sub-4-core hash default is 1; compression still
# wins there). Worker count is a THROUGHPUT knob only — block bytes are
# a pure function of (level, block size), identical at every count.

_compress_workers_override: "contextvars.ContextVar[int | None]" = \
    contextvars.ContextVar("makisu_compress_workers", default=None)


def default_compress_workers() -> int:
    return min(8, os.cpu_count() or 1)


def compress_workers() -> int:
    """Effective block-compress lane count for this context."""
    override = _compress_workers_override.get()
    if override is not None:
        return max(1, override)
    env = os.environ.get("MAKISU_TPU_COMPRESS_WORKERS", "")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass  # config never fails a build
    return default_compress_workers()


def set_compress_workers(n: int | None):
    """Bind a per-context lane count (the CLI flag). Returns a token
    for :func:`reset_compress_workers`."""
    return _compress_workers_override.set(n)


def reset_compress_workers(token) -> None:
    _compress_workers_override.reset(token)


# Shared hash-service batch linger (ms). Lives here — stdlib-only, no
# chunker import — so the CLI can read/set it without dragging jax into
# non-build invocations. Process-wide by design: the hash service
# batches ACROSS builds, so there is one linger per process.
_DEFAULT_LINGER_MS = 2.0
_linger_override_ms: float | None = None


def set_hash_linger_ms(ms: float | None) -> None:
    """Process-wide linger override (the ``--hash-linger-ms`` flag).
    Takes effect for hash services constructed afterwards — the worker
    sets it before its first build creates the shared service."""
    global _linger_override_ms
    _linger_override_ms = ms


def hash_linger_ms() -> float:
    """Effective linger in ms: flag override, else env
    MAKISU_TPU_HASH_LINGER_MS, else 2ms."""
    if _linger_override_ms is not None:
        return max(0.0, _linger_override_ms)
    env = os.environ.get("MAKISU_TPU_HASH_LINGER_MS", "")
    if env:
        try:
            return max(0.0, float(env))
        except ValueError:
            pass  # config never fails a build
    return _DEFAULT_LINGER_MS


_hash_pool = None
_hash_pool_lock = threading.Lock()


def hash_pool():
    """Process-wide thread pool behind the commit pipeline's parallel
    stages. Shared across concurrent builds (like the transfer engine);
    each pipeline bounds its OWN in-flight work to its ``hash_workers``
    so one build cannot monopolize the supply. Threads spawn lazily, so
    the generous cap costs nothing on small hosts.

    First use also drops the GIL switch interval from CPython's 5ms
    default to 1ms (process-wide; MAKISU_TPU_SWITCH_INTERVAL_MS tunes
    it, ``0`` leaves the default). The commit pipeline's producer
    thread is GIL-bound between its blocking points, and at 5ms a pool
    task's entry can stall a full interval behind it — measured as the
    difference between pooled stages scaling and pooled stages LOSING
    to serial."""
    global _hash_pool
    with _hash_pool_lock:
        if _hash_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            try:
                ms = float(os.environ.get(
                    "MAKISU_TPU_SWITCH_INTERVAL_MS", "1"))
            except ValueError:
                ms = 1.0
            if ms > 0:
                import sys
                sys.setswitchinterval(ms / 1000.0)
            _hash_pool = ThreadPoolExecutor(
                max_workers=max(8, os.cpu_count() or 1),
                thread_name_prefix="commit-pipe")
        return _hash_pool


def submit_ctx(pool, fn: Callable[..., Any], *args: Any):
    """``pool.submit`` with the caller's contextvars carried into the
    task (same reason as :func:`ctx_map`: pool threads start with an
    empty context, which would strand stage telemetry in the global
    registry)."""
    ctx = contextvars.copy_context()
    return pool.submit(ctx.run, fn, *args)


def ctx_map(pool, fn: Callable[[Any], Any],
            items: Iterable[Any]) -> list:
    """``pool.map`` with the caller's contextvars carried into every
    task. Pool worker threads start with an EMPTY context, so without
    this a parallel layer transfer loses the build's telemetry
    registry — its requests would stamp the process-global trace id
    instead of the build's, and its counters would miss the per-build
    report. Each task runs in its own copy of the caller's context
    (one ``Context`` object cannot be entered concurrently)."""
    jobs = [(contextvars.copy_context(), item) for item in items]
    return list(pool.map(lambda job: job[0].run(fn, job[1]), jobs))


class WorkerPool:
    def __init__(self, workers: int, queue_depth: int | None = None) -> None:
        if workers < 1:
            raise ValueError("workers must be >= 1")
        self._tasks: queue.Queue = queue.Queue(queue_depth or workers)
        self._errors: list[BaseException] = []
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"workerpool-{i}")
            for i in range(workers)
        ]
        for t in self._threads:
            t.start()

    def _run(self) -> None:
        while True:
            task = self._tasks.get()
            if task is None:
                return
            if not self._stopped.is_set():
                try:
                    task()
                except BaseException as e:  # noqa: BLE001
                    with self._lock:
                        self._errors.append(e)
            self._tasks.task_done()

    def submit(self, fn: Callable[[], None]) -> None:
        """Enqueue work; blocks when the queue is full (backpressure).
        The submitter's contextvars (build telemetry registry, log
        sink) travel with the task, same as :func:`ctx_map`."""
        if self._stopped.is_set():
            raise RuntimeError("pool is stopped")
        ctx = contextvars.copy_context()
        self._tasks.put(lambda: ctx.run(fn))

    def stop(self) -> None:
        """Drop not-yet-started tasks and join workers."""
        self._stopped.set()
        self.wait()

    def wait(self) -> list[BaseException]:
        """Join all queued work, shut down workers, return errors."""
        for _ in self._threads:
            self._tasks.put(None)
        for t in self._threads:
            t.join()
        with self._lock:
            return list(self._errors)
