"""Bounded worker pool and context-propagating parallel map.

Reference capability: lib/concurrency/worker_pool.go (fixed-N goroutine
pool; Do blocks when the queue is full; Stop/Wait join). Python's
ThreadPoolExecutor has an unbounded queue, which for layer transfers
means unbounded memory; this pool applies backpressure instead.
"""

from __future__ import annotations

import contextvars
import queue
import threading
from typing import Any, Callable, Iterable


def ctx_map(pool, fn: Callable[[Any], Any],
            items: Iterable[Any]) -> list:
    """``pool.map`` with the caller's contextvars carried into every
    task. Pool worker threads start with an EMPTY context, so without
    this a parallel layer transfer loses the build's telemetry
    registry — its requests would stamp the process-global trace id
    instead of the build's, and its counters would miss the per-build
    report. Each task runs in its own copy of the caller's context
    (one ``Context`` object cannot be entered concurrently)."""
    jobs = [(contextvars.copy_context(), item) for item in items]
    return list(pool.map(lambda job: job[0].run(fn, job[1]), jobs))


class WorkerPool:
    def __init__(self, workers: int, queue_depth: int | None = None) -> None:
        if workers < 1:
            raise ValueError("workers must be >= 1")
        self._tasks: queue.Queue = queue.Queue(queue_depth or workers)
        self._errors: list[BaseException] = []
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"workerpool-{i}")
            for i in range(workers)
        ]
        for t in self._threads:
            t.start()

    def _run(self) -> None:
        while True:
            task = self._tasks.get()
            if task is None:
                return
            if not self._stopped.is_set():
                try:
                    task()
                except BaseException as e:  # noqa: BLE001
                    with self._lock:
                        self._errors.append(e)
            self._tasks.task_done()

    def submit(self, fn: Callable[[], None]) -> None:
        """Enqueue work; blocks when the queue is full (backpressure).
        The submitter's contextvars (build telemetry registry, log
        sink) travel with the task, same as :func:`ctx_map`."""
        if self._stopped.is_set():
            raise RuntimeError("pool is stopped")
        ctx = contextvars.copy_context()
        self._tasks.put(lambda: ctx.run(fn))

    def stop(self) -> None:
        """Drop not-yet-started tasks and join workers."""
        self._stopped.set()
        self.wait()

    def wait(self) -> list[BaseException]:
        """Join all queued work, shut down workers, return errors."""
        for _ in self._threads:
            self._tasks.put(None)
        for t in self._threads:
            t.join()
        with self._lock:
            return list(self._errors)
