"""Bounded worker pool.

Reference capability: lib/concurrency/worker_pool.go (fixed-N goroutine
pool; Do blocks when the queue is full; Stop/Wait join). Python's
ThreadPoolExecutor has an unbounded queue, which for layer transfers
means unbounded memory; this pool applies backpressure instead.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable


class WorkerPool:
    def __init__(self, workers: int, queue_depth: int | None = None) -> None:
        if workers < 1:
            raise ValueError("workers must be >= 1")
        self._tasks: queue.Queue = queue.Queue(queue_depth or workers)
        self._errors: list[BaseException] = []
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"workerpool-{i}")
            for i in range(workers)
        ]
        for t in self._threads:
            t.start()

    def _run(self) -> None:
        while True:
            task = self._tasks.get()
            if task is None:
                return
            if not self._stopped.is_set():
                try:
                    task()
                except BaseException as e:  # noqa: BLE001
                    with self._lock:
                        self._errors.append(e)
            self._tasks.task_done()

    def submit(self, fn: Callable[[], None]) -> None:
        """Enqueue work; blocks when the queue is full (backpressure)."""
        if self._stopped.is_set():
            raise RuntimeError("pool is stopped")
        self._tasks.put(fn)

    def stop(self) -> None:
        """Drop not-yet-started tasks and join workers."""
        self._stopped.set()
        self.wait()

    def wait(self) -> list[BaseException]:
        """Join all queued work, shut down workers, return errors."""
        for _ in self._threads:
            self._tasks.put(None)
        for t in self._threads:
            t.join()
        with self._lock:
            return list(self._errors)
