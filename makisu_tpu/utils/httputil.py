"""HTTP plumbing for the registry/cache clients.

Reference capability: lib/utils/httputil/ (option-pattern Send:286 with
accepted-status checking, retry/backoff on 408/5xx and network errors,
TLS client config, https→http fallback :403-421, NetworkError
classification).

The ``Transport`` seam is what makes the registry client hermetically
testable: the real transport speaks urllib; fixtures replay canned
responses in-process (reference: mocks/net/http + registry fixtures).
"""

from __future__ import annotations

import dataclasses
import http.client
import socket
import ssl
import time
import urllib.error
import urllib.request
from typing import BinaryIO

from makisu_tpu.utils import metrics

RETRYABLE_CODES = {408, 502, 503, 504}


class HTTPError(Exception):
    def __init__(self, status: int, url: str, body: bytes = b"") -> None:
        super().__init__(f"HTTP {status} for {url}: {body[:200]!r}")
        self.status = status
        self.url = url
        self.body = body


class NetworkError(Exception):
    pass


@dataclasses.dataclass
class Response:
    status: int
    headers: dict[str, str]
    body: bytes
    # sha256 of the bytes written to ``stream_to`` (set only when the
    # body streamed to a file) — lets callers verify content digests
    # without a second full read of a multi-GB blob.
    stream_sha256: str = ""

    def header(self, name: str) -> str:
        return self.headers.get(name.lower(), "")


class Transport:
    """Performs one HTTP exchange. Bodies are fully materialized; layer
    blobs stream via chunked PATCH uploads so each exchange stays
    bounded."""

    def __init__(self, tls_verify: bool = True,
                 ca_cert: str | None = None,
                 client_cert: "tuple[str, str | None] | None" = None)\
            -> None:
        # client_cert: (cert_path, key_path); key None = embedded in the
        # cert PEM (load_cert_chain semantics).
        self.tls_verify = tls_verify
        self.ca_cert = ca_cert
        self.client_cert = client_cert

    def _ssl_context(self) -> ssl.SSLContext:
        ctx = ssl.create_default_context(cafile=self.ca_cert)
        if not self.tls_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if self.client_cert:
            ctx.load_cert_chain(*self.client_cert)
        return ctx

    def round_trip(self, method: str, url: str, headers: dict[str, str],
                   body: bytes | BinaryIO | None = None,
                   timeout: float = 60.0,
                   stream_to: str | None = None) -> Response:
        """One exchange. With ``stream_to`` set, a 2xx body streams to
        that file path in 1MiB chunks (Response.body stays empty) so
        multi-GB blobs never materialize in memory."""
        if hasattr(body, "read"):
            body = body.read()
        req = urllib.request.Request(url, data=body, method=method,
                                     headers=headers)
        opener = urllib.request.build_opener(
            _NoDelayHTTPHandler(),
            _NoDelayHTTPSHandler(context=self._ssl_context()),
            _NoRedirect())
        try:
            with opener.open(req, timeout=timeout) as resp:
                resp_headers = {k.lower(): v
                                for k, v in resp.headers.items()}
                if stream_to is not None and resp.status // 100 == 2:
                    import hashlib
                    digest = hashlib.sha256()
                    with open(stream_to, "wb") as out:
                        while True:
                            chunk = resp.read(1 << 20)
                            if not chunk:
                                break
                            digest.update(chunk)
                            out.write(chunk)
                    return Response(resp.status, resp_headers, b"",
                                    stream_sha256=digest.hexdigest())
                return Response(resp.status, resp_headers, resp.read())
        except urllib.error.HTTPError as e:
            data = e.read() if hasattr(e, "read") else b""
            return Response(e.code,
                            {k.lower(): v for k, v in e.headers.items()},
                            data)
        except (urllib.error.URLError, OSError, ssl.SSLError) as e:
            raise NetworkError(f"{method} {url}: {e}") from e


class _NoRedirect(urllib.request.HTTPRedirectHandler):
    """Registry clients must see 3xx themselves (upload Location flows)."""

    def redirect_request(self, *args, **kwargs):
        return None


# TCP_NODELAY on every client socket: urllib writes headers and body in
# separate sends, and Nagle holding the second send for the delayed ACK
# of the first costs ~40ms PER REQUEST. Chunk-granular dedup issues
# thousands of small blob requests per layer — measured ~50x wall-clock
# on the chunk push/fetch planes.


class _NoDelayHTTPConnection(http.client.HTTPConnection):
    def connect(self) -> None:
        super().connect()
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


class _NoDelayHTTPSConnection(http.client.HTTPSConnection):
    def connect(self) -> None:
        super().connect()
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


class _NoDelayHTTPHandler(urllib.request.HTTPHandler):
    def http_open(self, req):
        return self.do_open(_NoDelayHTTPConnection, req)


class _NoDelayHTTPSHandler(urllib.request.HTTPSHandler):
    def https_open(self, req):
        return self.do_open(_NoDelayHTTPSConnection, req,
                            context=self._context)


def send(transport: Transport, method: str, url: str,
         headers: dict[str, str] | None = None,
         body: bytes | None = None,
         accepted: tuple[int, ...] = (200,),
         retries: int = 3, backoff: float = 0.5,
         timeout: float = 60.0,
         allow_http_fallback: bool = False,
         stream_to: str | None = None) -> Response:
    """One request with retry/backoff on retryable statuses and network
    errors, optional https→http downgrade for plain-HTTP registries.

    Every request carries a W3C ``traceparent`` header naming the
    active build's trace and the innermost open span, so registry and
    cache-KV server logs correlate with the build's span tree /
    ``--trace-out`` export. Retries of one logical request reuse the
    same header — they ARE the same operation."""
    headers = dict(headers or {})
    headers.setdefault("traceparent", metrics.current_traceparent())
    last: Exception | None = None
    for attempt in range(retries):
        try:
            kwargs = {} if stream_to is None else {"stream_to": stream_to}
            resp = transport.round_trip(method, url, headers, body, timeout,
                                        **kwargs)
        except NetworkError as e:
            last = e
            if allow_http_fallback and url.startswith("https://"):
                url = "http://" + url[len("https://"):]
                continue
            time.sleep(backoff * (2 ** attempt))
            continue
        if resp.status in accepted:
            return resp
        if resp.status in RETRYABLE_CODES and attempt < retries - 1:
            time.sleep(backoff * (2 ** attempt))
            continue
        raise HTTPError(resp.status, url, resp.body)
    assert last is not None
    raise last
