"""HTTP plumbing for the registry/cache clients.

Reference capability: lib/utils/httputil/ (option-pattern Send:286 with
accepted-status checking, retry/backoff on 408/5xx and network errors,
TLS client config, https→http fallback :403-421, NetworkError
classification).

The ``Transport`` seam is what makes the registry client hermetically
testable: the real transport speaks http.client over a per-origin
keep-alive connection pool; fixtures replay canned responses in-process
(reference: mocks/net/http + registry fixtures).
"""

from __future__ import annotations

import dataclasses
import http.client
import socket
import ssl
import threading
import time
import urllib.parse
from typing import BinaryIO

from makisu_tpu.utils import metrics

RETRYABLE_CODES = {408, 502, 503, 504}

# Idle keep-alive connections kept per (scheme, host, port). Sized to
# the transfer engine's default concurrency: more idle sockets than
# concurrent requests would just hold fds a registry's LB will time out
# anyway.
POOL_MAX_IDLE = 16


class HTTPError(Exception):
    def __init__(self, status: int, url: str, body: bytes = b"") -> None:
        super().__init__(f"HTTP {status} for {url}: {body[:200]!r}")
        self.status = status
        self.url = url
        self.body = body


class NetworkError(Exception):
    pass


class _StaleConnection(Exception):
    """Internal: a pooled keep-alive connection failed before the
    server can have processed the request (send error, or closed with
    zero response bytes) — retry once on a fresh connection."""


@dataclasses.dataclass
class Response:
    status: int
    headers: dict[str, str]
    body: bytes
    # sha256 of the bytes written to ``stream_to`` (set only when the
    # body streamed to a file) — lets callers verify content digests
    # without a second full read of a multi-GB blob.
    stream_sha256: str = ""

    def header(self, name: str) -> str:
        return self.headers.get(name.lower(), "")


class Transport:
    """Performs one HTTP exchange over a per-origin keep-alive pool.

    Bodies are fully materialized; layer blobs stream via chunked PATCH
    uploads so each exchange stays bounded. Connections are reused
    across requests to the same (scheme, host, port): a registry pull
    of N blobs used to pay N TCP+TLS handshakes — with parallel chunk
    fetches that is thousands of handshakes per build, and handshake
    RTTs, not bytes, dominated the wire time. 3xx responses are
    returned to the caller, never followed (upload Location flows).
    Thread-safe: a connection is checked out for exactly one exchange.

    Known limitation vs the previous urllib transport: http(s)_proxy
    environment variables are not honored — connections go straight to
    the registry host. Registries only reachable through an egress
    proxy need a network-layer proxy (or a transport subclass).
    """

    def __init__(self, tls_verify: bool = True,
                 ca_cert: str | None = None,
                 client_cert: "tuple[str, str | None] | None" = None)\
            -> None:
        # client_cert: (cert_path, key_path); key None = embedded in the
        # cert PEM (load_cert_chain semantics).
        self.tls_verify = tls_verify
        self.ca_cert = ca_cert
        self.client_cert = client_cert
        self._pool: dict[tuple[str, str, int],
                         list[http.client.HTTPConnection]] = {}
        self._pool_lock = threading.Lock()
        self._ssl_ctx: ssl.SSLContext | None = None

    def _ssl_context(self) -> ssl.SSLContext:
        # Cached: one context serves every pooled connection (building
        # one per request would also defeat TLS session resumption).
        if self._ssl_ctx is None:
            ctx = ssl.create_default_context(cafile=self.ca_cert)
            if not self.tls_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if self.client_cert:
                ctx.load_cert_chain(*self.client_cert)
            self._ssl_ctx = ctx
        return self._ssl_ctx

    def _origin(self, url: str) -> tuple[str, str, int, str]:
        parts = urllib.parse.urlsplit(url)
        scheme = parts.scheme or "http"
        host = parts.hostname or ""
        port = parts.port or (443 if scheme == "https" else 80)
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query
        return scheme, host, port, path

    def _checkout(self, scheme: str, host: str, port: int,
                  timeout: float) -> tuple[http.client.HTTPConnection,
                                           bool]:
        """Pop an idle keep-alive connection for the origin, or open a
        fresh one. Returns (conn, reused)."""
        key = (scheme, host, port)
        with self._pool_lock:
            idle = self._pool.get(key)
            if idle:
                conn = idle.pop()
                conn.timeout = timeout
                if conn.sock is not None:
                    conn.sock.settimeout(timeout)
                return conn, True
        return self._new_conn(scheme, host, port, timeout), False

    def _new_conn(self, scheme: str, host: str, port: int,
                  timeout: float) -> http.client.HTTPConnection:
        if scheme == "https":
            return _NoDelayHTTPSConnection(host, port, timeout=timeout,
                                           context=self._ssl_context())
        return _NoDelayHTTPConnection(host, port, timeout=timeout)

    def _checkin(self, scheme: str, host: str, port: int,
                 conn: http.client.HTTPConnection) -> None:
        key = (scheme, host, port)
        with self._pool_lock:
            idle = self._pool.setdefault(key, [])
            if len(idle) < POOL_MAX_IDLE:
                idle.append(conn)
                return
        conn.close()

    def _flush_origin(self, scheme: str, host: str, port: int) -> None:
        with self._pool_lock:
            idle = self._pool.pop((scheme, host, port), [])
        for conn in idle:
            conn.close()

    def close(self) -> None:
        """Close every idle pooled connection (tests, engine teardown)."""
        with self._pool_lock:
            pools, self._pool = self._pool, {}
        for idle in pools.values():
            for conn in idle:
                conn.close()

    def round_trip(self, method: str, url: str, headers: dict[str, str],
                   body: bytes | BinaryIO | None = None,
                   timeout: float = 60.0,
                   stream_to: str | None = None) -> Response:
        """One exchange. With ``stream_to`` set, a 2xx body streams to
        that file path in 1MiB chunks (Response.body stays empty) so
        multi-GB blobs never materialize in memory."""
        if hasattr(body, "read"):
            body = body.read()
        scheme, host, port, path = self._origin(url)
        conn, reused = self._checkout(scheme, host, port, timeout)
        try:
            return self._exchange(conn, scheme, host, port, method, path,
                                  headers, body, stream_to,
                                  retry_stale=reused)
        except _StaleConnection:
            # The pooled connection had been quietly closed by the
            # server (keep-alive timeout): either the send itself
            # failed, or zero response bytes arrived — in both cases
            # the server did not process the request, so one retry is
            # safe for any method. The origin's remaining idle sockets
            # aged identically and are just as likely dead — flush
            # them now rather than paying one failed round trip each —
            # and the retry opens a genuinely fresh connection.
            self._flush_origin(scheme, host, port)
            conn = self._new_conn(scheme, host, port, timeout)
            try:
                return self._exchange(conn, scheme, host, port, method,
                                      path, headers, body, stream_to,
                                      retry_stale=False)
            except (http.client.HTTPException, OSError, ssl.SSLError) as e:
                conn.close()
                raise NetworkError(f"{method} {url}: {e}") from e
        except (http.client.HTTPException, OSError, ssl.SSLError) as e:
            raise NetworkError(f"{method} {url}: {e}") from e

    def _exchange(self, conn: http.client.HTTPConnection, scheme: str,
                  host: str, port: int, method: str, path: str,
                  headers: dict[str, str], body: bytes | None,
                  stream_to: str | None, retry_stale: bool) -> Response:
        fresh = conn.sock is None
        try:
            conn.request(method, path, body=body, headers=headers)
        except (http.client.HTTPException, OSError, ssl.SSLError):
            conn.close()
            if retry_stale:
                raise _StaleConnection() from None
            raise
        metrics.counter_add(metrics.HTTP_REQUESTS_TOTAL)
        if fresh:
            # request() opened the socket lazily; count the handshake
            # only once it actually happened.
            metrics.counter_add(metrics.HTTP_CONNECTIONS_TOTAL,
                                scheme=scheme)
        try:
            resp = conn.getresponse()
        except http.client.RemoteDisconnected:
            # Closed without ANY response bytes: the stale-keep-alive
            # signature. Errors mid-response (IncompleteRead etc.) are
            # NOT retried at this layer — the server may have acted on
            # a non-idempotent request; send()'s status-aware retry
            # owns that decision.
            conn.close()
            if retry_stale:
                raise _StaleConnection() from None
            raise
        except (http.client.HTTPException, OSError, ssl.SSLError):
            conn.close()
            raise
        resp_headers = {k.lower(): v for k, v in resp.getheaders()}
        try:
            if (stream_to is not None and resp.status // 100 == 2
                    and method != "HEAD"):
                import hashlib
                from makisu_tpu.utils import events as events_mod
                digest = hashlib.sha256()
                with open(stream_to, "wb") as out:
                    while True:
                        chunk = resp.read(1 << 20)
                        if not chunk:
                            break
                        digest.update(chunk)
                        out.write(chunk)
                        # Each landed buffer stamps the progress clock:
                        # a slow multi-GB streaming pull is PROGRESS,
                        # not a stall, even between telemetry events.
                        events_mod.note_progress()
                result = Response(resp.status, resp_headers, b"",
                                  stream_sha256=digest.hexdigest())
            else:
                result = Response(resp.status, resp_headers, resp.read())
        except BaseException:
            conn.close()  # a half-read body must never be pooled
            raise
        finally:
            resp.close()
        if resp.will_close:
            conn.close()
        else:
            self._checkin(scheme, host, port, conn)
        return result


# TCP_NODELAY on every client socket: http.client writes headers and
# body in separate sends, and Nagle holding the second send for the
# delayed ACK of the first costs ~40ms PER REQUEST. Chunk-granular
# dedup issues thousands of small blob requests per layer — measured
# ~50x wall-clock on the chunk push/fetch planes.


class _NoDelayHTTPConnection(http.client.HTTPConnection):
    def connect(self) -> None:
        super().connect()
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


class _NoDelayHTTPSConnection(http.client.HTTPSConnection):
    def connect(self) -> None:
        super().connect()
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


def send(transport: Transport, method: str, url: str,
         headers: dict[str, str] | None = None,
         body: bytes | None = None,
         accepted: tuple[int, ...] = (200,),
         retries: int = 3, backoff: float = 0.5,
         timeout: float = 60.0,
         allow_http_fallback: bool = False,
         stream_to: str | None = None) -> Response:
    """One request with retry/backoff on retryable statuses and network
    errors, optional https→http downgrade for plain-HTTP registries.

    Every request carries a W3C ``traceparent`` header naming the
    active build's trace and the innermost open span, so registry and
    cache-KV server logs correlate with the build's span tree /
    ``--trace-out`` export. Retries of one logical request reuse the
    same header — they ARE the same operation."""
    headers = dict(headers or {})
    headers.setdefault("traceparent", metrics.current_traceparent())
    last: Exception | None = None
    for attempt in range(retries):
        try:
            kwargs = {} if stream_to is None else {"stream_to": stream_to}
            resp = transport.round_trip(method, url, headers, body, timeout,
                                        **kwargs)
        except NetworkError as e:
            last = e
            if allow_http_fallback and url.startswith("https://"):
                url = "http://" + url[len("https://"):]
                continue
            time.sleep(backoff * (2 ** attempt))
            continue
        if resp.status in accepted:
            return resp
        if resp.status in RETRYABLE_CODES and attempt < retries - 1:
            time.sleep(backoff * (2 ** attempt))
            continue
        raise HTTPError(resp.status, url, resp.body)
    assert last is not None
    raise last
