"""``makisu-tpu explain``: render cache-decision ledgers into answers.

Three questions, one subcommand (input: ``--explain-out`` ledgers from
``utils/ledger.py``, optionally the matching ``--metrics-out`` report):

- **Miss attribution** (``explain LEDGER``): which Dockerfile node
  broke the cache chain, why (reason per consult), which files' changed
  bytes broke it (stat-cache blame), and what the chunk plane did about
  it (dedup ratio, bytes refetched per layer).
- **Build-to-build diff** (``explain LEDGER --baseline OLD``): exactly
  which keys flipped hit→miss between two builds, with the file-level
  blame and the re-chunked byte delta.
- **Warm-rebuild floor profile** (``explain LEDGER --metrics
  report.json``): per-phase wall-time breakdown split into
  *cache-avoidable* (goes away when every consult hits) vs the
  *irreducible floor* (startup + context scan — what the sub-10s
  incremental target has to attack), reusing ``traceexport``'s
  phase/self-time machinery.

All pure functions over loaded dicts — the CLI wiring lives in
``cli.cmd_explain``; tests golden these renderings directly.
"""

from __future__ import annotations

from typing import Any

from makisu_tpu.utils import traceexport
from makisu_tpu.utils.traceexport import fmt_bytes

# Verdicts that mean "the build had to redo work for this key".
MISS_VERDICTS = ("miss", "stale", "error")


def _label(decision: dict) -> str:
    """Human node label for one decision: ``stage 0 step 2 COPY``."""
    parts = []
    if decision.get("stage") is not None:
        parts.append(f"stage {decision['stage']}")
    if decision.get("step") is not None:
        parts.append(f"step {decision['step']}")
    if decision.get("directive"):
        parts.append(str(decision["directive"]))
    return " ".join(parts) if parts else "(no node in scope)"


def _by_source(ledger: dict, source: str) -> list[dict]:
    return [d for d in ledger.get("decisions", [])
            if d.get("source") == source]


def kv_chain(ledger: dict) -> list[dict]:
    """The build's KV consults in build order, one per key (a key
    re-consulted after the prefetch keeps its FIRST verdict — that is
    the decision that shaped the build)."""
    seen: set[str] = set()
    chain: list[dict] = []
    for decision in _by_source(ledger, "kv"):
        key = str(decision.get("key", ""))
        if key in seen:
            continue
        seen.add(key)
        chain.append(decision)
    return chain


def statcache_blame(ledger: dict) -> dict[str, dict]:
    """Stat-cache decisions keyed by the step cache ID they produced —
    the file-level blame for a flipped COPY/ADD key."""
    return {str(d.get("key", "")): d
            for d in _by_source(ledger, "statcache")}


def _verdict_tag(decision: dict) -> str:
    verdict = str(decision.get("verdict", "?"))
    reason = decision.get("reason")
    return f"{verdict} ({reason})" if reason else verdict


# -- miss attribution -------------------------------------------------------


def render_explain(ledger: dict, report: dict | None = None) -> str:
    header = ledger.get("header", {})
    summary = ledger.get("summary", {})
    lines: list[str] = []
    lines.append("makisu-tpu cache explain — command: "
                 f"{header.get('command') or '?'}")
    if header.get("trace_id"):
        lines.append(f"trace id: {header['trace_id']}")
    verdicts = summary.get("verdicts", {})
    lines.append(
        f"decisions: {summary.get('decisions', 0)}  ("
        + "  ".join(f"{v}={n}" for v, n in sorted(verdicts.items()))
        + ")")
    if summary.get("recomputed"):
        lines.append("(summary recomputed: ledger torn before its "
                     "summary line)")

    chain = kv_chain(ledger)
    blame = statcache_blame(ledger)
    lines.append("")
    if chain:
        lines.append("cache chain (KV consults, build order):")
        breaker: dict | None = None
        for decision in chain:
            verdict = decision.get("verdict")
            marker = ""
            if breaker is None and verdict in MISS_VERDICTS:
                breaker = decision
                marker = "  ← broke the cache chain"
            saved = int(decision.get("bytes_saved", 0) or 0)
            extra = f"  saved {fmt_bytes(saved)}" if saved else ""
            lines.append(
                f"  {_label(decision):<24s} {str(decision.get('key', '')):<18s}"
                f" {_verdict_tag(decision)}{extra}{marker}")
        if breaker is not None:
            key = str(breaker.get("key", ""))
            stat = blame.get(key)
            lines.append("")
            if stat and stat.get("changed_files"):
                changed = stat["changed_files"]
                misses = int(stat.get("misses", 0) or 0)
                total = int(stat.get("files", 0) or 0)
                lines.append(
                    f"blame ({_label(breaker)} key {key}): "
                    f"{misses}/{total} context files re-hashed")
                for rel in changed:
                    lines.append(f"    changed: {rel}")
                if misses > len(changed):
                    lines.append(
                        f"    … and {misses - len(changed)} more")
            else:
                lines.append(
                    f"blame ({_label(breaker)} key {key}): no stat-cache"
                    " record — not a COPY/ADD content change (directive"
                    ", args, or an upstream key changed)")
    else:
        lines.append("cache chain: no KV consults recorded")

    indexed = _by_source(ledger, "chunk_index")
    cas = _by_source(ledger, "chunk_cas")
    if indexed or cas:
        lines.append("")
        lines.append("chunk plane (per layer):")
        for decision in indexed:
            total = int(decision.get("bytes_total", 0) or 0)
            added = int(decision.get("bytes_added", 0) or 0)
            ratio = (1.0 - added / total) if total else 0.0
            lines.append(
                f"  indexed {str(decision.get('key', ''))[:16]}  "
                f"{decision.get('added', 0)}/{decision.get('chunks', 0)}"
                f" chunks new — re-chunked {fmt_bytes(added)} of "
                f"{fmt_bytes(total)} (dedup {100.0 * ratio:.1f}%)"
                f"  [{_label(decision)}]")
        for decision in cas:
            refetched = int(decision.get("bytes_refetched", 0) or 0)
            total = int(decision.get("bytes_total", 0) or 0)
            lines.append(
                f"  consult {str(decision.get('key', ''))[:16]}  "
                f"{decision.get('missing', 0)}/"
                f"{decision.get('requested', 0)} chunks missing — "
                f"{_verdict_tag(decision)}, refetched "
                f"{fmt_bytes(refetched)} of {fmt_bytes(total)}")

    lines.append("")
    lines.append(
        f"bytes: saved {fmt_bytes(summary.get('bytes_saved', 0))} from "
        f"cache · refetched {fmt_bytes(summary.get('bytes_refetched', 0))}"
        f" over the wire · re-chunked "
        f"{fmt_bytes(summary.get('bytes_added', 0))} "
        f"(dedup ratio {100.0 * summary.get('dedup_ratio', 0.0):.1f}%)")
    stat = summary.get("statcache", {})
    if stat.get("hits") or stat.get("misses"):
        lines.append(
            f"stat-cache: {stat.get('hits', 0)} hit / "
            f"{stat.get('misses', 0)} re-hashed"
            + (f" (changed: {', '.join(stat['changed_files'][:5])}"
               + ("…" if len(stat.get("changed_files", [])) > 5 else "")
               + ")" if stat.get("changed_files") else ""))

    if report is not None:
        lines.append("")
        lines.append(render_floor_profile(report, summary).rstrip("\n"))
    return "\n".join(lines) + "\n"


# -- build-to-build diff ----------------------------------------------------


def diff_ledgers(current: dict, baseline: dict) -> dict[str, Any]:
    """Structured build-to-build diff of the KV chains, joined by NODE
    POSITION (stage, step) — not raw key, because cache IDs are
    content-addressed: an edit does not flip a key's verdict, it mints
    a NEW key at that step (and chains downstream). A "flip" is
    therefore a node whose baseline consult succeeded and whose current
    one did not; ``key_changed`` marks the content-invalidation case
    (old key hit → new key miss) vs the same-key case (entry evicted /
    KV down)."""
    def by_node(ledger: dict) -> dict:
        return {(str(d.get("stage", "")), d.get("step")): d
                for d in kv_chain(ledger)}

    cur, base = by_node(current), by_node(baseline)
    flipped_miss = []   # hit/empty in baseline -> miss/stale/error now
    flipped_hit = []
    for node, decision in cur.items():
        old = base.get(node)
        if old is None:
            continue
        was_ok = old.get("verdict") not in MISS_VERDICTS
        is_ok = decision.get("verdict") not in MISS_VERDICTS
        entry = {"current": decision, "baseline": old,
                 "key_changed": (str(decision.get("key", ""))
                                 != str(old.get("key", "")))}
        if was_ok and not is_ok:
            flipped_miss.append(entry)
        elif not was_ok and is_ok:
            flipped_hit.append(entry)
    return {
        "flipped_to_miss": flipped_miss,
        "flipped_to_hit": flipped_hit,
        "only_current": [d for n, d in cur.items() if n not in base],
        # Baseline nodes with no current consult: usually the steps
        # BELOW the first break — the prefetch chain stopped before
        # reaching them.
        "only_baseline": [d for n, d in base.items() if n not in cur],
    }


def render_diff(current: dict, baseline: dict) -> str:
    lines: list[str] = []
    lines.append(
        "makisu-tpu cache diff — baseline "
        f"{baseline.get('header', {}).get('trace_id', '?')[:16]} → "
        f"current {current.get('header', {}).get('trace_id', '?')[:16]}")
    diff = diff_ledgers(current, baseline)
    blame = statcache_blame(current)

    lines.append("")
    flipped = diff["flipped_to_miss"]
    lines.append(f"nodes flipped hit→miss ({len(flipped)}):")
    for entry in flipped:
        decision, old = entry["current"], entry["baseline"]
        key, old_key = (str(decision.get("key", "")),
                        str(old.get("key", "")))
        if entry["key_changed"]:
            lines.append(
                f"  {_label(decision):<24s} key {old_key} → {key}  "
                f"(content changed)  {_verdict_tag(decision)}")
        else:
            lines.append(
                f"  {_label(decision):<24s} key {key}  (unchanged key"
                f" — entry lost)  {_verdict_tag(decision)}")
        stat = blame.get(key)
        if stat and stat.get("changed_files"):
            for rel in stat["changed_files"]:
                lines.append(f"      blame: {rel} changed "
                             "(stat-cache re-hash)")
    if not flipped:
        lines.append("  (none)")
    if diff["flipped_to_hit"]:
        lines.append("")
        lines.append(
            f"nodes flipped miss→hit ({len(diff['flipped_to_hit'])}):")
        for entry in diff["flipped_to_hit"]:
            decision = entry["current"]
            lines.append(f"  {_label(decision):<24s} "
                         f"{str(decision.get('key', '')):<18s} "
                         f"{_verdict_tag(decision)}")
    for field, title in (
            ("only_current", "nodes consulted only in current"),
            ("only_baseline",
             "nodes consulted only in baseline (current prefetch "
             "chain stopped above them)")):
        if diff[field]:
            lines.append("")
            lines.append(f"{title} ({len(diff[field])}):")
            for decision in diff[field]:
                lines.append(f"  {_label(decision):<24s} "
                             f"{str(decision.get('key', ''))}")

    cur_sum = current.get("summary", {})
    base_sum = baseline.get("summary", {})
    lines.append("")
    lines.append(
        "re-chunked bytes: baseline "
        f"{fmt_bytes(base_sum.get('bytes_added', 0))} → current "
        f"{fmt_bytes(cur_sum.get('bytes_added', 0))}; wire refetch: "
        f"baseline {fmt_bytes(base_sum.get('bytes_refetched', 0))} → "
        f"current {fmt_bytes(cur_sum.get('bytes_refetched', 0))}")
    return "\n".join(lines) + "\n"


# -- warm-rebuild floor profile ---------------------------------------------

# Floor-profile phases in render order. ``startup`` is everything not
# otherwise classified (process + backend init, config, report
# writing); ``context_scan`` is the BuildPlan construction span
# (stat-walk + re-hash of changed files).
FLOOR_PHASES = ("startup", "context_scan", "pull", "chunk", "hash",
                "push")


def _floor_phase(span_name: str) -> str:
    if span_name == "context_scan":
        return "context_scan"
    phase = traceexport.phase_of(span_name)
    return "startup" if phase == "other" else phase

# Phases a fully-warm cache removes entirely: layer commit (chunk +
# hash), pushes, and cache-driven transfers. Startup and the context
# scan are paid on EVERY build — the irreducible floor the
# always-warm/watch-mode work has to attack.
AVOIDABLE_PHASES = ("pull", "chunk", "hash", "push")


def floor_profile(report: dict,
                  summary: dict | None = None) -> list[dict]:
    """Per-phase self-time rows with the irreducible-vs-cache-avoidable
    split. ``summary`` (a ledger summary) refines the labels: with
    misses recorded, the avoidable time is miss-driven; with a fully
    hit ledger it is residual floor the cache did NOT remove."""
    totals = {phase: 0.0 for phase in FLOOR_PHASES}
    for name, self_t in traceexport.self_time_by_name(report).items():
        totals[_floor_phase(name)] += self_t
    misses = 0
    if summary:
        verdicts = summary.get("verdicts", {})
        misses = sum(int(verdicts.get(v, 0)) for v in MISS_VERDICTS)
    rows = []
    for phase in FLOOR_PHASES:
        avoidable = phase in AVOIDABLE_PHASES
        if avoidable:
            classification = ("cache-avoidable (miss-driven)"
                              if misses else
                              "residual despite full cache hit")
        elif phase == "context_scan":
            classification = ("irreducible floor (stat-walk; re-hash "
                              "part is cache-avoidable)")
        else:
            classification = "irreducible floor (startup)"
        rows.append({"phase": phase, "seconds": totals[phase],
                     "avoidable": avoidable,
                     "class": classification})
    return rows


def render_floor_profile(report: dict,
                         summary: dict | None = None) -> str:
    top = traceexport.root_span(report)
    total = float((top or {}).get("duration") or 0.0)
    rows = floor_profile(report, summary)
    lines = [f"warm-rebuild floor profile (wall {total:.3f}s):"]
    for row in rows:
        pct = 100.0 * row["seconds"] / total if total else 0.0
        lines.append(f"  {row['phase']:<13s} {row['seconds']:8.3f}s "
                     f"{pct:5.1f}%  {row['class']}")
    avoidable = sum(r["seconds"] for r in rows if r["avoidable"])
    floor = sum(r["seconds"] for r in rows if not r["avoidable"])
    lines.append(
        f"  cache-avoidable {avoidable:.3f}s · irreducible floor "
        f"{floor:.3f}s — the floor is what watch-mode/persistent-state"
        " work must attack")
    return "\n".join(lines) + "\n"
