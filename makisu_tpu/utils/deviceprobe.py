"""Device-session ledger: one durable record per backend-probe attempt.

Every bench round r01–r05 died at device-backend init with nothing
finer than ``"died in: backend"`` — each attempt's evidence (how far
init got, where it parked, which attachment it was pointed at) lived
and died with the process. This module is the cross-session record:
every probe attempt — bench child, build-path ``backend_ready()``,
worker warm probe — appends a ``makisu-tpu.deviceprobe.v1`` line to
``benchmarks/device_sessions/device_probes.jsonl`` (the artifact
bench.py has promised in comments since round 3; failed sessions are
exactly the data the device-route fix needs).

Record shape (written by ``ops/backend.py``'s watcher thread):

    {"schema": "makisu-tpu.deviceprobe.v1", "ts": ..., "pid": ...,
     "source": "build|worker|bench",
     "platform": "<JAX_PLATFORMS or (default)>",
     "attachment": {"key": <hashed attachment-env fingerprint>,
                    "vars": [<attachment var NAMES present>]},
     "verdict": "ok|failed|wedged|ok_late|failed_late",
     "detail": "...", "timeout_seconds": N, "total_seconds": N,
     "phase_reached": "<last phase that completed>",
     "wedged_phase": "<phase executing when the budget elapsed>",
     "phases": [{"phase", "seconds", "ok"}, ...],
     "samples": [{"frame", "count", "stack": [...]}, ...]}

``samples`` is the stack-sample trajectory: the known wedge parks the
probe thread inside a C call where no exception ever fires, so the
deepest-Python-frame trajectory ("12 identical samples inside
make_c_api_client") is the only diagnosis available.

``makisu-tpu doctor --device`` (:func:`render_device_doctor`) reads
the whole ledger and answers the cross-session questions: which phase
dominates the wedges, at which frame, per-attachment verdict history,
and when the route was last healthy.

Path resolution: ``$MAKISU_TPU_DEVICE_SESSIONS_DIR`` wins (empty value
disables recording entirely); unset, the ledger lands next to the
bench evidence files in ``<repo>/benchmarks/device_sessions``.
Recording is additionally gated by ``ops/backend.py`` on a device
actually being configured, so CPU-only runs don't write unless the
env var opts them in (CI's healthy-path smoke does exactly that).

Like the rest of the telemetry layer: stdlib-only, append-only
``O_APPEND`` single-write lines (concurrent processes share the file
safely), and never able to fail a build.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any

SCHEMA = "makisu-tpu.deviceprobe.v1"
LEDGER_BASENAME = "device_probes.jsonl"

# Verdicts meaning "the backend never became usable in budget".
_BAD_VERDICTS = ("wedged", "failed", "failed_late")


def sessions_dir() -> str | None:
    """The device-session ledger directory, or None when recording is
    disabled (``MAKISU_TPU_DEVICE_SESSIONS_DIR=""``)."""
    env = os.environ.get("MAKISU_TPU_DEVICE_SESSIONS_DIR")
    if env is not None:
        return env or None
    import makisu_tpu
    repo = os.path.dirname(os.path.dirname(
        os.path.abspath(makisu_tpu.__file__)))
    return os.path.join(repo, "benchmarks", "device_sessions")


def ledger_path() -> str | None:
    d = sessions_dir()
    return os.path.join(d, LEDGER_BASENAME) if d else None


def append_record(record: dict) -> str | None:
    """Append one record as a single ``O_APPEND`` write (POSIX keeps
    concurrent writers' lines whole — a worker's warm probe and a
    bench child can share the file). Returns the path written, or
    None when recording is disabled."""
    path = ledger_path()
    if path is None:
        return None
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    line = json.dumps(record, separators=(",", ":"),
                      default=str) + "\n"
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, line.encode())
    finally:
        os.close(fd)
    return path


def read_records(path: str | None = None) -> list[dict]:
    """Load deviceprobe records from a ledger file, a sessions
    directory (every ``*.jsonl`` inside — the bench evidence files
    interleave, their non-matching schemas are skipped), or the
    default directory (``path=None``). Missing paths yield ``[]``;
    torn final lines of a killed process are salvaged like every
    other JSONL artifact."""
    from makisu_tpu.utils import events
    if path is None:
        path = sessions_dir()
    if not path:
        return []
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, name) for name in os.listdir(path)
            if name.endswith(".jsonl"))
    elif os.path.exists(path):
        files = [path]
    else:
        return []
    records: list[dict] = []
    for name in files:
        try:
            lines = events.read_jsonl(name, skip_invalid=True)
        except OSError:
            continue
        records.extend(r for r in lines if r.get("schema") == SCHEMA)
    records.sort(key=lambda r: r.get("ts", 0.0))
    return records


# -- cross-session diagnosis (`makisu-tpu doctor --device`) ----------------


def _fmt_when(ts: float | None) -> str:
    if not ts:
        return "?"
    return time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime(ts))


def _dominant_sample(record: dict) -> dict | None:
    """The longest-held deepest frame of one attempt's trajectory."""
    samples = record.get("samples") or []
    if not samples:
        return None
    return max(samples, key=lambda s: int(s.get("count", 0)))


def render_device_doctor(records: list[dict]) -> str:
    """Human diagnosis across every recorded probe attempt: verdict
    counts, the dominant wedge phase and frame, per-attachment
    history, the last healthy window, and healthy-path phase
    timings."""
    lines: list[str] = []
    n = len(records)
    lines.append(f"makisu-tpu doctor — device route "
                 f"({n} probe attempt{'s' if n != 1 else ''})")
    by_verdict: dict[str, int] = {}
    by_source: dict[str, int] = {}
    for r in records:
        by_verdict[r.get("verdict", "?")] = \
            by_verdict.get(r.get("verdict", "?"), 0) + 1
        by_source[r.get("source", "?")] = \
            by_source.get(r.get("source", "?"), 0) + 1
    lines.append("attempts: " + "  ".join(
        f"{v}×{c}" for v, c in sorted(by_verdict.items()))
        + "   sources: " + " ".join(
        f"{s}×{c}" for s, c in sorted(by_source.items())))

    diagnosis: list[str] = []
    wedged = [r for r in records if r.get("verdict") == "wedged"]
    bad = [r for r in records if r.get("verdict") in _BAD_VERDICTS]
    ok = [r for r in records
          if r.get("verdict") in ("ok", "ok_late")]

    # -- dominant wedge ---------------------------------------------------
    if wedged:
        phases: dict[str, int] = {}
        for r in wedged:
            phase = r.get("wedged_phase") or "?"
            phases[phase] = phases.get(phase, 0) + 1
        phase, count = max(phases.items(), key=lambda kv: kv[1])
        lines.append("")
        lines.append(f"dominant wedge: phase '{phase}' "
                     f"({count} of {len(wedged)} wedged attempts)")
        last = max(wedged, key=lambda r: r.get("ts", 0.0))
        sample = _dominant_sample(last)
        frame = ""
        if sample:
            # "via": the caller above the representative frame — the
            # representative may sit above interpreter parking frames,
            # so locate it in the stack first.
            stack = sample.get("stack") or []
            via = ""
            if sample["frame"] in stack:
                i = stack.index(sample["frame"])
                if i + 1 < len(stack):
                    via = stack[i + 1]
            elif len(stack) > 1:
                via = stack[1]
            frame = sample["frame"] + (f" via {via}" if via else "")
            lines.append(
                f"  deepest frame: {frame} — "
                f"{sample.get('count', 0)} identical samples in the "
                f"last wedge")
        lines.append(
            f"  last wedge: {_fmt_when(last.get('ts'))} after "
            f"{last.get('total_seconds', 0):.0f}s "
            f"(pid {last.get('pid', '?')}, "
            f"source {last.get('source', '?')}, "
            f"reached '{last.get('phase_reached') or 'nothing'}')")
        diagnosis.append(
            f"backend init wedges in '{phase}'"
            + (f" at {frame}" if frame else "")
            + f" — {count}/{len(wedged)} wedged attempts agree")
    failed = [r for r in records
              if r.get("verdict") in ("failed", "failed_late")]
    if failed:
        last = max(failed, key=lambda r: r.get("ts", 0.0))
        lines.append("")
        lines.append(f"init failures: {len(failed)} (last: "
                     f"{_fmt_when(last.get('ts'))} — "
                     f"{last.get('detail', '?')[:120]})")
        if not wedged:
            diagnosis.append(
                f"backend init FAILS (raises) rather than wedging: "
                f"{last.get('detail', '?')[:120]}")

    # -- last healthy window ----------------------------------------------
    lines.append("")
    if ok:
        first_ok = min(ok, key=lambda r: r.get("ts", 0.0))
        last_ok = max(ok, key=lambda r: r.get("ts", 0.0))
        lines.append(
            f"last healthy: {_fmt_when(last_ok.get('ts'))} "
            f"(init {last_ok.get('total_seconds', 0):.1f}s, "
            f"platform {last_ok.get('platform', '?')}); "
            f"{len(ok)} ok attempt{'s' if len(ok) != 1 else ''} "
            f"since {_fmt_when(first_ok.get('ts'))}")
        bad_after = [r for r in bad
                     if r.get("ts", 0.0) > last_ok.get("ts", 0.0)]
        if bad_after:
            diagnosis.append(
                f"{len(bad_after)} failed/wedged attempt(s) SINCE the "
                f"last healthy init — the route regressed, it was not "
                f"always dead")
        # Healthy-path phase timings (p50 per phase across ok runs).
        from makisu_tpu.utils import metrics
        per_phase: dict[str, list[float]] = {}
        for r in ok:
            for p in r.get("phases") or []:
                if p.get("ok"):
                    per_phase.setdefault(p["phase"], []).append(
                        float(p.get("seconds", 0.0)))
        if per_phase:
            lines.append("healthy-path phase p50: " + "  ".join(
                f"{phase}={metrics.percentile(vals, 50):.2f}s"
                for phase, vals in per_phase.items()))
    else:
        lines.append("last healthy: never — no recorded attempt "
                     "reached a usable backend")
        if bad:
            diagnosis.append("no recorded attempt has EVER produced a "
                             "usable backend on this route")

    # -- per-attachment history -------------------------------------------
    by_attach: dict[str, list[dict]] = {}
    for r in records:
        key = (r.get("attachment") or {}).get("key", "?")
        by_attach.setdefault(key, []).append(r)
    if by_attach:
        lines.append("")
        lines.append(f"per-attachment history "
                     f"({len(by_attach)} attachment"
                     f"{'s' if len(by_attach) != 1 else ''}):")
        for key, recs in sorted(by_attach.items()):
            verdicts: dict[str, int] = {}
            for r in recs:
                verdicts[r.get("verdict", "?")] = \
                    verdicts.get(r.get("verdict", "?"), 0) + 1
            last = max(recs, key=lambda r: r.get("ts", 0.0))
            env_vars = (last.get("attachment") or {}).get("vars") or []
            lines.append(
                f"  {key[:12]}…  "
                + " ".join(f"{v}×{c}"
                           for v, c in sorted(verdicts.items()))
                + f"   last {last.get('verdict', '?')} "
                f"{_fmt_when(last.get('ts'))}"
                + (f"   vars: {', '.join(env_vars[:4])}"
                   + ("…" if len(env_vars) > 4 else "")
                   if env_vars else ""))

    lines.append("")
    if diagnosis:
        lines.append("diagnosis: " + "; ".join(diagnosis) + ".")
    else:
        lines.append("diagnosis: device route healthy — every recorded "
                     "attempt reached a usable backend.")
    return "\n".join(lines) + "\n"


def tail(limit: int = 6, path: str | None = None) -> dict[str, Any]:
    """Compact ledger digest for embedding (the BENCH record's
    ``device_sessions`` block): record count, verdict counts, and the
    last few attempts."""
    records = read_records(path)
    verdicts: dict[str, int] = {}
    for r in records:
        verdicts[r.get("verdict", "?")] = \
            verdicts.get(r.get("verdict", "?"), 0) + 1
    return {
        "records": len(records),
        "verdicts": dict(sorted(verdicts.items())),
        "tail": [{
            "ts": r.get("ts"),
            "source": r.get("source"),
            "verdict": r.get("verdict"),
            "phase": r.get("wedged_phase") or r.get("phase_reached"),
            "total_seconds": r.get("total_seconds"),
        } for r in records[-limit:]],
    }
