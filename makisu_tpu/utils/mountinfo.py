"""Mount table: which paths are mountpoints / under mounts.

Used to skip mounted paths during untar and layer scans so bind-mounted
files (k8s configmaps, /etc/resolv.conf, build volumes) never leak into
image layers. Reference capability: lib/mountutils/ (initialize at
mountutils.go:55, IsMountpoint:128, IsMounted:135, ContainsMountpoint:141).
"""

from __future__ import annotations

import os
import threading

_MOUNTINFO = "/proc/self/mountinfo"

_lock = threading.Lock()
_mountpoints: set[str] | None = None


def _load() -> set[str]:
    global _mountpoints
    with _lock:
        if _mountpoints is None:
            points: set[str] = set()
            try:
                with open(_MOUNTINFO) as f:
                    for line in f:
                        # field 5 (0-indexed 4) is the mount point; octal
                        # escapes like \040 encode spaces.
                        fields = line.split()
                        if len(fields) > 4:
                            mp = fields[4].encode().decode("unicode_escape")
                            points.add(os.path.normpath(mp))
            except OSError:
                pass
            _mountpoints = points
        return _mountpoints


def set_mountpoints_for_testing(points: set[str] | None) -> None:
    global _mountpoints
    with _lock:
        _mountpoints = points


def is_mountpoint(path: str) -> bool:
    """True if path is exactly a mount point (root "/" excluded)."""
    p = os.path.normpath(path)
    return p != "/" and p in _load()


def is_mounted(path: str) -> bool:
    """True if path is a mount point or inside one (other than "/")."""
    p = os.path.normpath(path)
    for mp in _load():
        if mp == "/":
            continue
        if p == mp or p.startswith(mp.rstrip("/") + "/"):
            return True
    return False


def contains_mountpoint(path: str) -> bool:
    """True if any mount point sits at or below path."""
    p = os.path.normpath(path).rstrip("/")
    for mp in _load():
        if mp == "/":
            continue
        if mp == p or mp.startswith(p + "/"):
            return True
    return False
