"""OS-level helpers: special files, stat fields, user/group resolution.

Reference capability: lib/utils/utils.go (IsSpecialFile:161, FileInfoStat:167,
ResolveChown:190).
"""

from __future__ import annotations

import os
import stat


def is_special_file(st: os.stat_result) -> bool:
    """Sockets, fifos, and device nodes never belong in image layers."""
    mode = st.st_mode
    return (stat.S_ISSOCK(mode) or stat.S_ISFIFO(mode)
            or stat.S_ISBLK(mode) or stat.S_ISCHR(mode))


def resolve_chown(chown: str) -> tuple[int, int]:
    """``user[:group]`` (names or numeric ids) → (uid, gid).

    A bare user with no group maps the group to the same value, matching
    docker's --chown semantics. Empty string → (0, 0).
    """
    if not chown:
        return 0, 0
    parts = chown.split(":")
    if len(parts) > 2:
        raise ValueError(f"malformed chown argument: {chown!r}")
    user = parts[0]
    group = parts[1] if len(parts) == 2 else user

    def _uid(name: str) -> int:
        if name.isdigit():
            return int(name)
        import pwd
        return pwd.getpwnam(name).pw_uid

    def _gid(name: str) -> int:
        if name.isdigit():
            return int(name)
        import grp
        return grp.getgrnam(name).gr_gid

    return _uid(user), _gid(group)
