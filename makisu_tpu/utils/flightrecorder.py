"""Failure forensics: flight recorder, stall watchdog, diagnostic bundles.

Everything in the telemetry layer so far explains builds that FINISH —
the span tree materializes at exit, ``makisu-tpu report`` reads a
complete ``--metrics-out`` file. A build that hangs, OOMs, or is
SIGTERM'd by a CI timeout leaves nothing. This module is the black box
for those builds:

- :class:`FlightRecorder` — a per-build bounded ring buffer holding the
  last-N build events (subscribed to ``utils/events.py``), recent log
  records (via the ``utils/logging.py`` tap), and whatever the resource
  sampler (``utils/resources.py``) has collected. Always armed by
  ``cli.main``; costs a lock-free deque append per event.
- :func:`FlightRecorder.dump` — renders one JSON **diagnostic bundle**:
  the ring buffers, every open span with its age, all-thread stack
  traces (``sys._current_frames``), the transfer engine's in-flight
  state, a metrics snapshot, and build identity. Written atomically;
  triggered on build failure, stall, SIGTERM, or SIGUSR1.
- :class:`StallWatchdog` — a daemon thread that fires a ``stall`` event
  and dumps a bundle when the event bus and the transfer engine both
  make no progress for a configurable window. The idle clock is
  :func:`last_progress_seconds`, which the worker's ``/healthz`` also
  reports.
- :func:`render_doctor` — the ``makisu-tpu doctor BUNDLE`` output: a
  human diagnosis (stuck span, wedged thread, resource trajectory)
  from a bundle.

Signal-safety: bundles can be produced from inside a SIGTERM handler
running in the main thread, which may have interrupted code holding
telemetry locks. Every structure the dump path reads is therefore
either lock-free (ring deques, the open-span dict) or probed with a
timeout and skipped when unavailable (the metrics registry lock) —
a dump degrades, it never deadlocks the dying process.
"""

from __future__ import annotations

import collections
import os
import sys
import tempfile
import threading
import time
import traceback
from typing import Any

import makisu_tpu
from makisu_tpu.utils import events
from makisu_tpu.utils import logging as log
from makisu_tpu.utils import metrics

BUNDLE_SCHEMA = "makisu-tpu.flightrecorder.v1"
DEFAULT_EVENTS_KEEP = 256
DEFAULT_LOGS_KEEP = 64


def last_progress_seconds(cell: list | None = None) -> float:
    """Seconds since the last observable progress. With no ``cell``:
    process-wide — the newest of the event bus's last emit and the
    transfer engine's last completed work (the worker's ``/healthz``
    field and its process watchdog). With a per-build progress cell
    (``events.bind_progress_cell``): that build's own clock, so a
    wedged build's watchdog is not masked by healthy siblings."""
    if cell is not None:
        return max(time.monotonic() - cell[0], 0.0)
    marks = [events.last_emit_monotonic()]
    try:
        from makisu_tpu.registry import transfer
        marks.append(transfer.last_progress_monotonic())
    except Exception:  # noqa: BLE001 - forensics never fails the caller
        pass
    return max(time.monotonic() - max(marks), 0.0)


def thread_stacks() -> list[dict]:
    """All-thread stack traces via ``sys._current_frames``, newest
    frame last (traceback order). Lock-free: safe from a signal
    handler."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in sorted(frames.items()):
        thread = by_ident.get(ident)
        # format_stack entries are "File ..., line N, in f\n    code";
        # flatten to one string per line so consumers (and doctor's
        # frame parser) never meet embedded newlines.
        stack = [line for entry in traceback.format_stack(frame)
                 for line in entry.rstrip("\n").split("\n")]
        out.append({
            "name": thread.name if thread else f"thread-{ident}",
            "ident": ident,
            "daemon": bool(thread.daemon) if thread else None,
            "stack": stack,
        })
    return out


def _transfer_state() -> dict | None:
    """The transfer engine's in-flight snapshot, or None when no
    transfer has ever run in this process."""
    try:
        from makisu_tpu.registry import transfer
        engine = transfer.peek()
    except Exception:  # noqa: BLE001
        return None
    return engine.snapshot() if engine is not None else None


def _device_probe_state() -> dict | None:
    """The backend probe's phase/sample snapshot, or None when the
    device plane was never touched. Lock-free underneath
    (``ops/backend.py`` tracker stores are GIL-atomic), so safe from
    signal context; the ops package is only consulted when something
    already imported it — a bundle must not pay a jax import."""
    if "makisu_tpu.ops.backend" not in sys.modules:
        return None
    try:
        return sys.modules["makisu_tpu.ops.backend"].probe_snapshot()
    except Exception:  # noqa: BLE001 - forensics never fails the dump
        return None


def _metrics_snapshot(reg: "metrics.MetricsRegistry") -> dict | None:
    """``reg.report()`` guarded for signal context: if the interrupted
    main thread holds the registry lock the probe times out and the
    bundle ships without a metrics section instead of deadlocking."""
    if not reg._lock.acquire(timeout=0.5):
        return None
    reg._lock.release()  # report() re-acquires; probe proved it's free
    return reg.report()


def _bundle_name(reason: str, tag: str) -> str:
    """``tag`` (a truncated trace id) disambiguates concurrent builds
    in one worker PROCESS — without it, two builds failing seconds
    apart would resolve the same pid-keyed path and the second dump
    would silently replace the first build's forensics."""
    middle = f"{tag}-" if tag else ""
    return f"makisu-tpu-diag-{os.getpid()}-{middle}{reason}.json"


def resolve_bundle_path(diag_out: str, reason: str,
                        tag: str = "") -> str | None:
    """Where a bundle should land: an explicit ``--diag-out`` wins,
    then ``$MAKISU_TPU_DIAG_DIR`` (CI sets this so red runs upload the
    bundle as an artifact), else None — failure dumps are opt-in."""
    if diag_out:
        return diag_out
    diag_dir = os.environ.get("MAKISU_TPU_DIAG_DIR", "")
    if diag_dir:
        try:
            os.makedirs(diag_dir, exist_ok=True)
        except OSError:
            return None
        return os.path.join(diag_dir, _bundle_name(reason, tag))
    return None


def forced_bundle_path(diag_out: str, reason: str, tag: str = "") -> str:
    """Like :func:`resolve_bundle_path` but never None: stalls and
    signals always leave a bundle somewhere (the tempdir as a last
    resort) — those are exactly the deaths that otherwise leave no
    trace."""
    return (resolve_bundle_path(diag_out, reason, tag) or
            os.path.join(tempfile.gettempdir(),
                         _bundle_name(reason, tag)))


def forced_profile_path(diag_out: str, reason: str, tag: str = "") -> str:
    """Where an on-demand profile snapshot (SIGUSR2) lands: next to an
    explicit ``--diag-out`` bundle (never ON it — the profile must not
    clobber captured forensics), else ``$MAKISU_TPU_DIAG_DIR``, else
    the tempdir. Never None, same contract as
    :func:`forced_bundle_path`."""
    middle = f"{tag}-" if tag else ""
    name = f"makisu-tpu-profile-{os.getpid()}-{middle}{reason}.json"
    if diag_out:
        parent = os.path.dirname(diag_out) or "."
        try:
            os.makedirs(parent, exist_ok=True)
        except OSError:
            pass
        return os.path.join(parent, name)
    diag_dir = os.environ.get("MAKISU_TPU_DIAG_DIR", "")
    if diag_dir:
        try:
            os.makedirs(diag_dir, exist_ok=True)
            return os.path.join(diag_dir, name)
        except OSError:
            pass
    return os.path.join(tempfile.gettempdir(), name)


class FlightRecorder:
    """Bounded in-memory record of one build (or one process, when
    armed globally by the worker). All appends are lock-free deque
    writes; readers take snapshots with a retry so a dump racing an
    append can never block or corrupt."""

    def __init__(self, events_keep: int = DEFAULT_EVENTS_KEEP,
                 logs_keep: int = DEFAULT_LOGS_KEEP) -> None:
        self._events: "collections.deque[dict]" = \
            collections.deque(maxlen=events_keep)
        self._logs: "collections.deque[dict]" = \
            collections.deque(maxlen=logs_keep)
        self.armed_at = time.time()
        self.dumped = False
        self.dumped_reasons: set[str] = set()
        self.last_dump_path: str | None = None

    def captured_terminal_moment(self) -> bool:
        """Whether a dump already froze the INTERESTING moment — a
        stall or a kill signal. A SIGUSR1 inspection poke doesn't
        count: it must not suppress the eventual failure bundle."""
        return bool(self.dumped_reasons & {"stall", "SIGTERM"})

    # -- feeds ------------------------------------------------------------

    def record_event(self, event: dict) -> None:
        """Event-bus sink (bind with ``events.add_sink``)."""
        self._events.append(event)

    def record_log(self, level: str, msg: str, fields: dict) -> None:
        """Log tap (bind with ``logging.add_tap``)."""
        record = {"ts": round(time.time(), 6), "level": level, "msg": msg}
        if fields:
            record["fields"] = dict(fields)
        self._logs.append(record)

    @staticmethod
    def _snapshot(ring: "collections.deque[dict]") -> list[dict]:
        return metrics.snapshot_concurrent(ring)

    # -- bundles ----------------------------------------------------------

    def bundle(self, reason: str,
               registry: "metrics.MetricsRegistry | None" = None,
               **extra: Any) -> dict[str, Any]:
        """Assemble the diagnostic bundle. ``registry`` defaults to the
        context's active one — a watchdog running in the build's copied
        context or a signal handler in a standalone build both resolve
        to the build registry; the worker's process-level recorder
        resolves to the global one."""
        from makisu_tpu.utils import resources
        reg = registry if registry is not None else \
            metrics.active_registry()
        open_spans = metrics.open_span_snapshot()
        if reg is not metrics.global_registry():
            # A per-build bundle must not blame another build: in a
            # worker the open-span set spans every registry, and the
            # doctor's stuck-span verdict would otherwise pick a
            # healthy sibling's long-running span. (Process-level
            # bundles — the worker's — keep the full view.)
            open_spans = [s for s in open_spans
                          if s["trace_id"] == reg.trace_id]
        out: dict[str, Any] = {
            "schema": BUNDLE_SCHEMA,
            "reason": reason,
            "ts": round(time.time(), 6),
            "build": {
                "trace_id": reg.trace_id,
                "pid": os.getpid(),
                "version": makisu_tpu.__version__,
                "argv0": sys.argv[0] if sys.argv else "",
                "recorder_armed_at": round(self.armed_at, 6),
            },
            "last_progress_seconds": round(last_progress_seconds(), 3),
            "events": self._snapshot(self._events),
            "logs": self._snapshot(self._logs),
            "open_spans": open_spans,
            "threads": thread_stacks(),
            "transfer": _transfer_state(),
            "resources": resources.trajectory(),
            "device_probe": _device_probe_state(),
            "profile": _profile_tail(),
        }
        out["metrics"] = _metrics_snapshot(reg)
        out.update(extra)
        return out

    def dump(self, path: str, reason: str,
             registry: "metrics.MetricsRegistry | None" = None,
             **extra: Any) -> str:
        """Write the bundle atomically and remember that we did — a
        later generic failure dump must not overwrite the stacks a
        stall or SIGTERM captured at the interesting moment."""
        metrics.write_json_atomic(path,
                                  self.bundle(reason, registry, **extra))
        self.dumped = True
        self.dumped_reasons.add(reason)
        self.last_dump_path = path
        # The counter bump takes every target registry's non-reentrant
        # lock; from a signal handler the interrupted frame may HOLD
        # one. Probe each with a timeout and skip the counter rather
        # than deadlock the dying process (same discipline as
        # _metrics_snapshot).
        for reg in metrics._targets():
            if not reg._lock.acquire(timeout=0.2):
                break
            reg._lock.release()
        else:
            metrics.counter_add("makisu_diag_bundles_total",
                                reason=reason)
        return path


def _profile_tail(limit: int = 40) -> dict | None:
    """A trimmed snapshot of the process sampler for embedding in
    diagnostic bundles: the hottest ``limit`` folded stacks plus the
    sampler's vitals. None when no sampler is armed. Lock-free reads
    only — bundles are assembled from signal handlers."""
    from makisu_tpu.utils import profiler
    sampler = profiler.process_profiler()
    if sampler is None or not sampler.samples_total:
        return None
    doc = sampler.snapshot()
    doc["stacks"] = doc["stacks"][:limit]
    doc.pop("traces", None)
    return doc


def install(recorder: FlightRecorder) -> tuple:
    """Bind a recorder to the current context's event bus and log tap.
    Returns tokens for :func:`uninstall`."""
    return (events.add_sink(recorder.record_event),
            log.add_tap(recorder.record_log))


def uninstall(tokens: tuple) -> None:
    events_token, log_token = tokens
    log.reset_tap(log_token)
    events.reset_sink(events_token)


def install_signal_dumps(recorder: FlightRecorder,
                         registry: "metrics.MetricsRegistry | None",
                         diag_out: str, tag: str = "") -> dict:
    """Bind SIGTERM (dump, then unwind via ``SystemExit(143)`` so open
    reports/logs still flush), SIGUSR1 (dump and keep running — live
    inspection), and SIGUSR2 (write the process sampler's profile
    snapshot and keep running — on-demand "where is the time going"
    without stopping the build) to ``recorder``. Main thread only —
    elsewhere (worker build handler threads) this is a no-op. Returns
    the replaced handlers for :func:`restore_signal_handlers`."""
    import signal
    old: dict = {}
    if threading.current_thread() is not threading.main_thread():
        return old

    def _dump(signum, frame, exit_after):
        name = signal.Signals(signum).name
        try:
            recorder.dump(forced_bundle_path(diag_out, name, tag=tag),
                          name, registry)
        except Exception:  # noqa: BLE001 - dying is the priority
            pass
        if exit_after:
            raise SystemExit(128 + signum)

    def _profile_dump(signum, frame):
        # Resolved at fire time, not registration time: the worker
        # arms its sampler after installing handlers, and a build with
        # --profile-hz 0 simply has nothing to dump.
        from makisu_tpu.utils import profiler
        name = signal.Signals(signum).name
        sampler = profiler.process_profiler()
        if sampler is None:
            return
        try:
            profiler.write_artifact(
                forced_profile_path(diag_out, name, tag=tag),
                sampler.snapshot(command=name))
        except Exception as e:  # noqa: BLE001 - forensics never kills work
            # Signal context: the logging plane takes sink locks, so
            # the trace goes straight to fd 2 (async-signal-safe).
            try:
                os.write(2, f"{name} profile dump failed: {e}\n".encode())
            except OSError:
                pass

    for sig, exit_after in ((signal.SIGTERM, True),
                            (signal.SIGUSR1, False)):
        try:
            old[sig] = signal.signal(
                sig, lambda s, f, e=exit_after: _dump(s, f, e))
        except (ValueError, OSError):  # pragma: no cover
            pass
    try:
        old[signal.SIGUSR2] = signal.signal(signal.SIGUSR2,
                                            _profile_dump)
    except (ValueError, OSError):  # pragma: no cover
        pass
    return old


def restore_signal_handlers(old: dict) -> None:
    import signal
    for sig, handler in old.items():
        try:
            signal.signal(sig, handler)
        except (ValueError, OSError):  # pragma: no cover
            pass


class StallWatchdog:
    """Fires when the build makes no observable progress for ``window``
    seconds: emits a ``stall`` event (into the build's own event sinks —
    the thread runs under the creator's copied context, so the event
    also lands in ``--events-out``), snapshots thread stacks into a
    bundle, and publishes ``makisu_build_stalled``. Re-arms once
    progress resumes, so a build that stalls twice dumps twice (the
    second dump overwrites — latest wedge wins)."""

    def __init__(self, window: float, recorder: FlightRecorder,
                 bundle_path: str,
                 registry: "metrics.MetricsRegistry | None" = None,
                 active_fn=None,
                 cell: list | None = None) -> None:
        self.window = max(float(window), 0.1)
        self.recorder = recorder
        self.bundle_path = bundle_path
        self.registry = registry
        # Gate: only consider idleness a stall while work is actually
        # in flight. A per-build watchdog is always "active" (a build
        # is by definition running); the worker's process watchdog
        # passes active_builds > 0 so an idle worker never dumps.
        self.active_fn = active_fn
        # Per-build progress cell (events.bind_progress_cell): this
        # watchdog watches ONE build's clock. None = process-wide.
        self.cell = cell
        self._stop = threading.Event()
        self._fired = False
        self._thread: threading.Thread | None = None

    def _set_stalled(self, value: float) -> None:
        # Per-build watchdogs label their series by trace id so
        # concurrent watchdogs in one worker can't overwrite each
        # other; the process watchdog owns the unlabeled series.
        labels = ({"trace_id": self.registry.trace_id}
                  if self.cell is not None and self.registry is not None
                  else {})
        metrics.global_registry().gauge_set("makisu_build_stalled",
                                            value, **labels)

    def _tick(self) -> None:
        if self.active_fn is not None and not self.active_fn():
            self._fired = False
            self._set_stalled(0.0)
            return
        idle = last_progress_seconds(self.cell)
        self._set_stalled(1.0 if idle >= self.window else 0.0)
        if idle < self.window:
            self._fired = False
            return
        if self._fired:
            return
        self._fired = True
        events.emit("stall", idle_seconds=round(idle, 3),
                    window_seconds=self.window)
        metrics.counter_add("makisu_stalls_total")
        try:
            # The stall emit itself just stamped the progress clock;
            # the bundle must carry the idle gap that TRIGGERED it.
            self.recorder.dump(self.bundle_path, "stall", self.registry,
                               last_progress_seconds=round(idle, 3))
            log.warning(
                "build stalled: no progress for %.1fs (window %.1fs); "
                "diagnostic bundle written to %s",
                idle, self.window, self.bundle_path)
        except Exception as e:  # noqa: BLE001 - forensics never kills a build
            log.warning("stall bundle write failed: %s", e)

    def _run(self) -> None:
        # This thread's emits/logs (the stall event, the bundle-written
        # warning) must not stamp the progress clock it polls — a
        # permanent wedge fires ONCE and last_progress_seconds keeps
        # climbing for /healthz.
        events.suppress_progress_stamps()
        interval = min(max(self.window / 4.0, 0.05), 5.0)
        while not self._stop.wait(interval):
            try:
                self._tick()
            except Exception:  # noqa: BLE001
                pass

    def start(self) -> "StallWatchdog":
        import contextvars
        # Copy the creator's context so stall events reach the build's
        # own sinks (events-out file, worker stream, recorder).
        ctx = contextvars.copy_context()
        self._thread = threading.Thread(
            target=ctx.run, args=(self._run,),
            name="stall-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        # Clear our gauge series: a long-lived worker must not report
        # a finished build as stalled forever.
        self._set_stalled(0.0)


def stall_timeout_from_env() -> float:
    """``MAKISU_TPU_STALL_TIMEOUT`` seconds; 0/unset/garbage = off."""
    try:
        return max(float(os.environ.get(
            "MAKISU_TPU_STALL_TIMEOUT", "") or 0.0), 0.0)
    except ValueError:
        return 0.0


# -- `makisu-tpu doctor` ----------------------------------------------------


def _fmt_bytes(n: float) -> str:
    from makisu_tpu.utils import traceexport
    return traceexport.fmt_bytes(n)


# Threads that exist BECAUSE of the forensics layer; never the wedge.
_FORENSIC_THREADS = ("stall-watchdog", "resource-sampler",
                     "profiler-sampler")
_FORENSIC_FILES = ("flightrecorder.py", "resources.py", "profiler.py")


def _thread_busy(thread: dict) -> bool:
    """A thread is interesting when any frame of its stack is in
    makisu-tpu code: a parked pool worker shows only stdlib plumbing
    (queue.get, Condition.wait), while a thread wedged mid-transfer
    has project frames above its blocking stdlib call — the innermost
    frame alone cannot tell them apart. The forensics layer's own
    frames (the thread doing the dump) don't count as work."""
    if thread.get("name") in _FORENSIC_THREADS:
        return False
    return any("makisu_tpu" in line
               and not any(f in line for f in _FORENSIC_FILES)
               for line in thread["stack"])


def _innermost(stack: list[str], skip_forensics: bool = False) -> str:
    """'func (file:line)' of a formatted stack's deepest frame.
    ``skip_forensics`` skips the dump machinery's own frames — a
    SIGTERM handler's MainThread stack ends inside the recorder, but
    the wedge is the frame below it."""
    for line in reversed(stack):
        line = line.strip()
        if not line.startswith("File "):
            continue
        if skip_forensics and any(f in line for f in _FORENSIC_FILES):
            continue
        try:
            path, lineno, func = line.split(", ", 2)
            name = os.path.basename(path.split('"')[1])
            return (f"{func.removeprefix('in ')} "
                    f"({name}:{lineno.removeprefix('line ')})")
        except (IndexError, ValueError):
            return line
    return stack[-1].strip() if stack else "?"


def render_doctor(bundle: dict) -> str:
    """Human diagnosis of a diagnostic bundle: what was stuck, which
    threads were wedged where, and how resources were trending when
    the build died."""
    lines: list[str] = []
    build = bundle.get("build", {})
    reason = bundle.get("reason", "?")
    ts = bundle.get("ts")
    when = (time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime(ts))
            if ts else "?")
    lines.append(f"makisu-tpu doctor — bundle reason: {reason}")
    lines.append(f"captured: {when}  pid: {build.get('pid', '?')}  "
                 f"version: {build.get('version', '?')}")
    if build.get("trace_id"):
        lines.append(f"trace id: {build['trace_id']}")
    idle = bundle.get("last_progress_seconds")
    if idle is not None:
        lines.append(f"last progress: {idle:.1f}s before capture")

    # -- stuck spans ------------------------------------------------------
    open_spans = bundle.get("open_spans") or []
    lines.append("")
    diagnosis: list[str] = []
    if open_spans:
        lines.append(f"open spans at capture ({len(open_spans)}):")
        for span in open_spans:
            attrs = span.get("attrs") or {}
            detail = ", ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
            label = span["name"] + (f" [{detail}]" if detail else "")
            leaf = " ◀ stuck here" if span.get("leaf") else ""
            lines.append(f"  {label:<44s} open "
                         f"{span.get('age_seconds', 0.0):8.1f}s{leaf}")
        leaves = [s for s in open_spans if s.get("leaf")]
        pick = max(leaves or open_spans,
                   key=lambda s: s.get("age_seconds", 0.0))
        diagnosis.append(
            f"build appears stuck in span '{pick['name']}' "
            f"(open {pick.get('age_seconds', 0.0):.1f}s)")
    else:
        lines.append("no spans were open at capture (the build was "
                     "between operations, or telemetry was torn down)")

    # -- threads ----------------------------------------------------------
    threads = bundle.get("threads") or []
    busy = [t for t in threads if _thread_busy(t)]
    lines.append("")
    lines.append(f"threads: {len(threads)} total, "
                 f"{len(busy)} with makisu-tpu frames")
    for t in threads[:16]:
        marker = "  ◀ busy" if t in busy else ""
        lines.append(f"  {t['name']:<24s} "
                     f"{_innermost(t['stack'])}{marker}")
    if len(threads) > 16:
        lines.append(f"  ... and {len(threads) - 16} more")
    for t in busy[:4]:
        lines.append("")
        lines.append(f"  stack of {t['name']}:")
        for frame in t["stack"][-8:]:
            lines.append(f"    {frame.strip()}")
    # A wedged-thread verdict only makes sense when the capture froze a
    # LIVE wedge (stall/signal); a failure bundle's stacks are post-hoc
    # — the build already unwound to the dump site.
    if busy and reason != "failure":
        wedge = next((t for t in busy if t["name"] != "MainThread"),
                     busy[0])
        diagnosis.append(
            f"thread '{wedge['name']}' wedged in "
            f"{_innermost(wedge['stack'], skip_forensics=True)}")

    # -- transfer engine --------------------------------------------------
    transfer = bundle.get("transfer")
    lines.append("")
    if transfer:
        lines.append(
            f"transfer engine: {transfer.get('queue_depth', 0)} tasks "
            f"in flight, "
            f"{_fmt_bytes(transfer.get('inflight_bytes', 0))} of "
            f"{_fmt_bytes(transfer.get('budget_limit_bytes', 0))} "
            f"budget reserved, concurrency "
            f"{transfer.get('concurrency', '?')}")
        if transfer.get("queue_depth", 0) > 0:
            diagnosis.append(
                f"{transfer['queue_depth']} transfer task(s) never "
                f"completed — suspect a wedged registry connection")
    else:
        lines.append("transfer engine: never used in this process")

    # -- device probe -----------------------------------------------------
    probe = bundle.get("device_probe") or {}
    state = probe.get("state", "")
    if state and state not in ("absent", "disabled"):
        lines.append("")
        desc = f"device probe: {state}"
        if probe.get("phase"):
            desc += f", in phase '{probe['phase']}'"
        elif probe.get("phase_reached"):
            desc += f", reached '{probe['phase_reached']}'"
        if probe.get("elapsed_seconds") is not None:
            desc += f", {probe['elapsed_seconds']:.0f}s elapsed"
        if probe.get("sample_count"):
            desc += f", {probe['sample_count']} stack samples"
        lines.append(desc)
        if probe.get("deepest_frame"):
            lines.append(f"  deepest sampled frame: "
                         f"{probe['deepest_frame']}")
        if state in ("wedged", "pending") and probe.get("phase"):
            diagnosis.append(
                f"backend init {state} in probe phase "
                f"'{probe['phase']}'"
                + (f" at {probe['deepest_frame']}"
                   if probe.get("deepest_frame") else ""))
        elif state == "failed" and probe.get("detail"):
            diagnosis.append(
                f"backend init failed: {probe['detail'][:120]}")

    # -- continuous profile -----------------------------------------------
    prof = bundle.get("profile") or {}
    if prof.get("samples"):
        from makisu_tpu.utils import profiler
        total = prof["samples"]
        lines.append("")
        lines.append(
            f"profile: {total} samples over "
            f"{prof.get('duration_seconds', 0.0):.1f}s at "
            f"{prof.get('hz', 0.0):g} Hz, sampler overhead "
            f"{100.0 * prof.get('overhead_fraction', 0.0):.2f}%")
        phases = prof.get("phases") or {}
        for phase, count in sorted(phases.items(),
                                   key=lambda kv: -kv[1])[:5]:
            hot = profiler.dominant_frame(prof, phase)
            detail = (f" — hottest frame {hot[0]} ({hot[1]} samples)"
                      if hot else "")
            lines.append(f"  {phase:<6s} {100.0 * count / total:5.1f}%"
                         f"{detail}")
        # A phase that owns most of the wall clock gets its hottest
        # frame named in the verdict — the attribution `history diff`
        # and SLO alerts can only gesture at.
        top_phase, top_count = max(phases.items(),
                                   key=lambda kv: kv[1],
                                   default=("", 0))
        hot = profiler.dominant_frame(prof, top_phase) \
            if top_phase else None
        if hot and top_count / total >= 0.5:
            diagnosis.append(
                f"phase '{top_phase}' dominates the profile "
                f"({100.0 * top_count / total:.0f}% of samples), "
                f"mostly in {hot[0]}")

    # -- resources --------------------------------------------------------
    samples = bundle.get("resources") or []
    lines.append("")
    if samples:
        first, last = samples[0], samples[-1]
        peak = max(s.get("rss_bytes", 0) for s in samples)
        lines.append(
            f"resources ({len(samples)} samples): rss "
            f"{_fmt_bytes(first.get('rss_bytes', 0))} → peak "
            f"{_fmt_bytes(peak)} → {_fmt_bytes(last.get('rss_bytes', 0))}"
            f", cpu {last.get('cpu_seconds', 0.0):.1f}s"
            + (f", {last['open_fds']} open fds"
               if "open_fds" in last else ""))
        if (last.get("rss_bytes", 0) > 0.9 * peak and
                peak > 2 * max(first.get("rss_bytes", 0), 1)):
            diagnosis.append("RSS was climbing at capture — possible "
                             "memory exhaustion")
    else:
        lines.append("resources: no samples recorded")

    # -- recent events ----------------------------------------------------
    tail = (bundle.get("events") or [])[-8:]
    if tail:
        lines.append("")
        lines.append(f"last {len(tail)} events:")
        base = tail[-1].get("ts", 0.0)
        for event in tail:
            extras = {k: v for k, v in event.items()
                      if k not in ("ts", "type")}
            detail = " ".join(f"{k}={v}" for k, v in sorted(extras.items()))
            dt = event.get("ts", 0.0) - base
            lines.append(f"  {dt:+8.2f}s  {event.get('type', '?'):<12s} "
                         f"{detail}"[:100])

    lines.append("")
    if diagnosis:
        lines.append("diagnosis: " + "; ".join(diagnosis) + ".")
    else:
        lines.append("diagnosis: nothing conclusive — the process was "
                     "idle and consistent at capture; check the event "
                     "tail and logs above for the last thing it did.")
    return "\n".join(lines) + "\n"
