"""Per-build environment expansion.

Steps must never mutate ``os.environ``: a worker runs many builds in one
process, and ARG/ENV exports from concurrent builds would interleave
(the reference can afford process-env mutation only because it is
one-process-per-build, base_step.go:95-108). Each BuildContext carries
its own env dict; this helper expands ``$VAR``/``${VAR}`` against it
with the same leave-unknown-untouched semantics as os.path.expandvars.
"""

from __future__ import annotations

import re

# re.ASCII matches posixpath._varprog: non-ASCII "word" characters are
# not variable names to expandvars, so not to us either.
_VAR = re.compile(r"\$(\w+|\{[^}]*\})", re.ASCII)


def expand(text: str, env: dict[str, str]) -> str:
    """Expand $VAR and ${VAR} from ``env``; unknown vars stay verbatim."""
    def sub(m: re.Match) -> str:
        name = m.group(1)
        if name.startswith("{"):
            name = name[1:-1]
        return env.get(name, m.group(0))
    return _VAR.sub(sub, text)
