"""Stat-keyed content-ID cache: skip re-hashing unchanged context files.

COPY/ADD cache identity covers the bytes being copied, so every build
hashes its context — at the north-star scale that is 100k files / 4GB
re-read on every warm rebuild whose content didn't change. This cache
remembers each file's content crc32 keyed by the stat quadruple
``(size, mtime_ns, ctime_ns, inode)``; a warm build re-hashes only
files whose stat changed. The keying is the git index discipline:
mtime+size alone can be spoofed by an editor that restores timestamps,
but a content write always bumps ctime (utime cannot restore it), so a
stale hit requires deliberately lying to the filesystem, not normal
tooling. ``MAKISU_TPU_STAT_CACHE=0`` disables the shortcut (every file
re-reads); either way the cache ID format is identical, so toggling
the switch never invalidates caches.

The reference re-hashes the full context every build
(lib/builder/step/add_copy_step.go SetCacheID); this is the buildkit
-style refinement of the same identity.

Racy-stat discipline (git's "racily clean" rule): a same-size edit in
the same timestamp tick as the hash would alias the stat key on
filesystems with coarse timestamps, so an entry is only TRUSTED when
the file's timestamps predate the recorded hash time by more than the
coarsest plausible granularity (2s, covering 1s filesystems). Files
touched within that window of being hashed simply re-hash next build —
a bounded perf cost, never a stale identity.
"""

from __future__ import annotations

import json
import os
import threading
import time

VERSION = 2
# A cached entry is trusted only if the file's mtime/ctime are at least
# this much older than the moment it was hashed (coarsest common fs
# timestamp granularity, with margin).
RACY_WINDOW_NS = 2_000_000_000
# Entries not touched by the saving build are kept up to this many
# (other contexts share a storage dir); beyond it, untouched entries
# age out oldest-file-first is overkill — drop arbitrarily.
MAX_CARRIED_ENTRIES = 1_000_000


def enabled() -> bool:
    return os.environ.get("MAKISU_TPU_STAT_CACHE", "1") == "1"


def racy_window_ns() -> int:
    """MAKISU_TPU_STAT_CACHE_WINDOW_NS overrides the racily-clean
    window (tests; operators on known-fine-grained filesystems)."""
    try:
        return int(os.environ.get("MAKISU_TPU_STAT_CACHE_WINDOW_NS",
                                  str(RACY_WINDOW_NS)))
    except ValueError:
        return RACY_WINDOW_NS


class ContentIDCache:
    """Per-storage-dir persistent map: rel path -> (stat key, crc32)."""

    def __init__(self, path: str, namespace: str = "") -> None:
        self.path = path
        # Entries are scoped by the build context dir (git scopes its
        # index per worktree the same way): different contexts sharing
        # one storage dir have colliding rel paths.
        self._ns = namespace + "\x00"
        self._lock = threading.Lock()
        self._entries: dict[str, list] | None = None  # lazy load
        self._touched: set[str] = set()
        self._dirty = False
        # Resident sessions flip this on: save() then runs on a
        # background thread (serializing 100k entries is seconds of
        # JSON on the warm path, and a resident process persists for
        # durability only — the live dict is the source of truth).
        self.defer_save = False
        self._saver: threading.Thread | None = None
        # Keys written since the last drain_mutations(): the session
        # snapshot writer's dirty-shard signal (worker/snapshots.py) —
        # an idle checkpoint must not re-serialize 100k clean entries.
        self._mutated: set[str] = set()

    def _load_locked(self) -> dict[str, list]:
        if self._entries is None:
            self._entries = {}
            try:
                with open(self.path, encoding="utf-8") as f:
                    rec = json.load(f)
                # Shape-validate everything: the file is shared state a
                # foreign tool or partial write can mangle, and an
                # advisory cache must start empty on ANY mismatch, not
                # crash every later build.
                if (isinstance(rec, dict)
                        and rec.get("version") == VERSION):
                    entries = rec.get("entries", {})
                    if isinstance(entries, dict):
                        self._entries = {
                            k: v for k, v in entries.items()
                            if isinstance(k, str)
                            and isinstance(v, list) and len(v) == 3
                            and isinstance(v[0], list)}
            except (OSError, ValueError):
                pass  # cache is advisory; start empty
        return self._entries

    @staticmethod
    def _key(st: os.stat_result) -> list:
        # st_dev: rel paths repeat across bind mounts / filesystems
        # where inode numbers restart; two contexts sharing a storage
        # dir must never alias.
        return [st.st_size, st.st_mtime_ns, st.st_ctime_ns, st.st_ino,
                st.st_dev]

    def get(self, rel: str, st: os.stat_result) -> int | None:
        return self.lookup(rel, st)[0]

    def lookup(self, rel: str,
               st: os.stat_result) -> tuple[int | None, str]:
        """``get`` plus WHY: ``(crc, "hit")`` on a trusted entry, else
        ``(None, reason)`` with reason one of ``disabled``, ``absent``
        (first sight of this path), ``stat_changed`` (an entry exists
        but the file's stat quadruple moved — a real content/metadata
        change, the blame signal ``makisu-tpu explain`` reports), or
        ``racy`` (entry too fresh to trust; a bounded re-hash, not a
        change)."""
        if not enabled():
            return None, "disabled"
        with self._lock:
            entry = self._load_locked().get(self._ns + rel)
            if entry is None:
                return None, "absent"
            if entry[0] != self._key(st):
                return None, "stat_changed"
            # Racily-clean guard: if the file was modified in the same
            # coarse-timestamp tick it was hashed in, the stat key
            # cannot distinguish a later same-size edit — re-hash.
            hashed_at = int(entry[2])
            newest = max(st.st_mtime_ns, st.st_ctime_ns)
            if hashed_at - newest < racy_window_ns():
                return None, "racy"
            self._touched.add(self._ns + rel)
            return int(entry[1]), "hit"

    def put(self, rel: str, st: os.stat_result, crc: int) -> None:
        with self._lock:
            self._load_locked()[self._ns + rel] = [
                self._key(st), int(crc), time.time_ns()]
            self._touched.add(self._ns + rel)
            self._mutated.add(self._ns + rel)
            self._dirty = True

    # -- session-snapshot surfaces (worker/snapshots.py) --

    def namespace_items(self) -> dict[str, list]:
        """Snapshot copy of this namespace's entries, keyed by REL path
        (the namespace prefix stripped — it is the context dir, which
        the snapshot recipe already carries)."""
        with self._lock:
            entries = self._load_locked()
            n = len(self._ns)
            return {k[n:]: list(v) for k, v in entries.items()
                    if k.startswith(self._ns)}

    def drain_mutations(self) -> set[str]:
        """Rel paths in this namespace written since the last drain
        (plus any foreign-namespace noise dropped silently)."""
        with self._lock:
            mutated = self._mutated
            self._mutated = set()
            n = len(self._ns)
            return {k[n:] for k in mutated if k.startswith(self._ns)}

    def merge_entries(self, entries: dict[str, list]) -> int:
        """Adopt restored snapshot entries (rel path → entry) that do
        not collide with fresher local knowledge. Entries keep their
        original ``hashed_at`` timestamps, so the racily-clean guard
        and the per-lookup stat comparison apply to them unchanged — a
        restored entry whose file moved since the snapshot reads
        ``stat_changed`` and re-hashes, never replays. Returns the
        number of entries adopted."""
        if not isinstance(entries, dict):
            return 0
        adopted = 0
        with self._lock:
            live = self._load_locked()
            for rel, entry in entries.items():
                if not (isinstance(rel, str) and isinstance(entry, list)
                        and len(entry) == 3
                        and isinstance(entry[0], list)):
                    continue
                key = self._ns + rel
                if key in live:
                    continue  # local knowledge is newer by definition
                live[key] = list(entry)
                adopted += 1
            if adopted:
                self._dirty = True
        return adopted

    def begin_build(self) -> None:
        """Reset the per-build touched set (a resident session reuses
        one instance across builds; pruning semantics must match a
        freshly-constructed cache every build)."""
        with self._lock:
            self._touched.clear()

    def save(self) -> None:
        """Atomic write-back via the shared fsync-then-rename helper
        (``fileio.write_json_atomic``): a SIGTERM landing mid-save —
        the CI-timeout kill unwinds SystemExit through here — leaves
        either the previous complete cache or the new one on disk,
        never a truncation that silently de-warms every later build.
        Still advisory: plain IO failures are swallowed (a cache that
        can't persist costs re-hashing, never correctness).

        Serialization runs on a SNAPSHOT outside the lock (concurrent
        lookups never stall behind a multi-MB json dump); with
        ``defer_save`` set (resident sessions) the whole write runs on
        a background thread — one saver at a time, the next save
        coalesces."""
        with self._lock:
            if not self._dirty or self._entries is None:
                return
            entries = self._entries
            if len(entries) > MAX_CARRIED_ENTRIES:
                entries = {rel: v for rel, v in entries.items()
                           if rel in self._touched}
            snapshot = dict(entries)
            self._dirty = False
            if self.defer_save:
                if self._saver is not None and self._saver.is_alive():
                    # A save is in flight with older state; mark dirty
                    # again so the NEXT save persists this one's news.
                    self._dirty = True
                    return
                self._saver = threading.Thread(
                    target=self._write, args=(snapshot,), daemon=True,
                    name="statcache-save")
                self._saver.start()
                return
        self._write(snapshot)

    def _write(self, entries: dict) -> None:
        from makisu_tpu.utils import fileio
        try:
            fileio.write_json_atomic(
                self.path, {"version": VERSION, "entries": entries})
        except OSError:
            with self._lock:
                self._dirty = True  # retry on the next save
