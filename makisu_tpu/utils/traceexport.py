"""Trace export and critical-path analysis of build telemetry reports.

Input everywhere is the ``--metrics-out`` report dict
(``metrics.MetricsRegistry.report()`` plus CLI extras): span tree,
counters, trace id. Two consumers:

- :func:`perfetto_trace` renders the span tree as Chrome/Perfetto
  trace-event JSON (the ``--trace-out`` file): complete ("X") events
  with microsecond timestamps, loadable in ui.perfetto.dev or
  chrome://tracing.
- :func:`render_report` is the ``makisu-tpu report`` subcommand's
  output: the longest span chain (the critical path through the nested
  timing tree — what to attack first to make the build faster), the
  top self-time sinks grouped into pull/chunk/hash/push phases, cache
  hit ratio, and bytes hashed per backend.

Self-time is a span's duration minus its children's — the time the
span itself burned. Summed over the tree it reconstructs the root's
wall time (concurrent children can push a span's child-sum past its
own duration; self-time floors at zero so aggregates stay sane).
"""

from __future__ import annotations

from typing import Any, Iterator

# Span-name substrings -> build phase, first match wins. Order matters:
# "pull_cache_layers" must classify as pull before "cache" could ever
# grow a phase of its own, and commit/hash both land in hash (layer
# commit IS the hashing path).
_PHASE_RULES: tuple[tuple[str, str], ...] = (
    ("pull", "pull"),
    ("from", "pull"),
    ("chunk", "chunk"),
    ("hash", "hash"),
    ("commit", "hash"),
    ("push", "push"),
)

PHASES = ("pull", "chunk", "hash", "push", "other")


def phase_of(span_name: str) -> str:
    name = span_name.lower()
    for needle, phase in _PHASE_RULES:
        if needle in name:
            return phase
    return "other"


def _walk(span: dict, depth: int = 0) -> Iterator[tuple[dict, int]]:
    yield span, depth
    for child in span.get("children", []):
        yield from _walk(child, depth + 1)


def _duration(span: dict) -> float:
    # Open spans (process died mid-span) carry null; treat as zero so
    # analysis of a torn report still works.
    return float(span.get("duration") or 0.0)


def root_span(report: dict) -> dict | None:
    """The invocation's top span (reports hold one top-level span per
    command; if several exist, the longest wins)."""
    spans = report.get("spans") or []
    if not spans:
        return None
    return max(spans, key=_duration)


# -- Perfetto / Chrome trace-event export ----------------------------------


def perfetto_trace(report: dict) -> dict:
    """Chrome trace-event JSON (the subset Perfetto loads) from a
    report's span tree. One complete ("X") slice per span; nesting
    falls out of timestamp containment on a single track. Span/trace
    ids and attrs ride in ``args`` so slices link back to event-log
    lines and server-side traceparent correlation."""
    trace_id = report.get("trace_id", "")
    slices: list[dict] = []
    for top in report.get("spans") or []:
        for span, _depth in _walk(top):
            event = {
                "name": span.get("name", "?"),
                "ph": "X",
                "ts": round(float(span.get("start", 0.0)) * 1e6, 3),
                "dur": round(_duration(span) * 1e6, 3),
                "pid": 1,
                "tid": 1,
                "cat": phase_of(span.get("name", "")),
                "args": {},
            }
            if span.get("span_id"):
                event["args"]["span_id"] = span["span_id"]
            if span.get("parent_id"):
                event["args"]["parent_id"] = span["parent_id"]
            if span.get("attrs"):
                event["args"].update(span["attrs"])
            if span.get("error"):
                event["args"]["error"] = span["error"]
            slices.append(event)
    out = {
        "traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 1,
             "args": {"name": f"makisu-tpu {report.get('command', '')}"
                      .strip()}},
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
             "args": {"name": "build"}},
            *slices,
        ],
        "displayTimeUnit": "ms",
    }
    if trace_id:
        out["otherData"] = {"trace_id": trace_id}
    return out


# -- critical path ---------------------------------------------------------


def critical_path(report: dict) -> list[dict]:
    """The longest span chain root→leaf: from each span, descend into
    the child that consumed the most wall time. Returns hops as
    ``{"name", "duration", "self", "depth", "attrs"}``; the first hop
    is the root, so the path's total IS the root's wall time — the
    chain tells you where that time concentrates."""
    top = root_span(report)
    if top is None:
        return []
    path: list[dict] = []
    span, depth = top, 0
    while span is not None:
        children = span.get("children", [])
        child_sum = sum(_duration(c) for c in children)
        path.append({
            "name": span.get("name", "?"),
            "duration": _duration(span),
            "self": max(_duration(span) - child_sum, 0.0),
            "depth": depth,
            "attrs": span.get("attrs", {}),
        })
        span = max(children, key=_duration) if children else None
        depth += 1
    return path


def self_time_by_name(report: dict) -> dict[str, float]:
    """Aggregate self-time per span name across the whole tree."""
    out: dict[str, float] = {}
    for top in report.get("spans") or []:
        for span, _depth in _walk(top):
            child_sum = sum(_duration(c)
                            for c in span.get("children", []))
            self_t = max(_duration(span) - child_sum, 0.0)
            name = span.get("name", "?")
            out[name] = out.get(name, 0.0) + self_t
    return out


def phase_totals(report: dict) -> dict[str, float]:
    """Self-time per build phase (pull/chunk/hash/push/other)."""
    totals = {phase: 0.0 for phase in PHASES}
    for name, self_t in self_time_by_name(report).items():
        totals[phase_of(name)] += self_t
    return totals


def open_spans_in(report: dict) -> list[dict]:
    """Spans with a null duration — open when the report was captured,
    i.e. the process died (or was snapshotted) mid-span. A finished
    build's report has none; a flight-recorder bundle's metrics
    snapshot typically has the whole stuck chain."""
    out = []
    for top in report.get("spans") or []:
        for span, depth in _walk(top):
            if span.get("duration") is None:
                out.append({
                    "name": span.get("name", "?"),
                    "depth": depth,
                    "start": float(span.get("start", 0.0)),
                    "attrs": span.get("attrs", {}),
                })
    return out


def resources_by_phase(report: dict) -> dict[str, dict[str, float]]:
    """Peak RSS and CPU seconds per build phase, from the per-span
    resource attribution the sampler recorded (utils/resources.py).
    Peak RSS is a max (it is a process-wide level observed while the
    span was open); CPU sums the per-leaf charges, so phases are
    roughly exclusive."""
    out: dict[str, dict[str, float]] = {}
    for top in report.get("spans") or []:
        for span, _depth in _walk(top):
            res = span.get("resources")
            if not res:
                continue
            phase = phase_of(span.get("name", ""))
            agg = out.setdefault(phase,
                                 {"peak_rss_bytes": 0.0,
                                  "cpu_seconds": 0.0})
            agg["peak_rss_bytes"] = max(agg["peak_rss_bytes"],
                                        float(res.get("peak_rss_bytes",
                                                      0)))
            agg["cpu_seconds"] += float(res.get("cpu_seconds", 0.0))
    return out


# -- counters --------------------------------------------------------------


def _counter_series(report: dict, name: str) -> list[dict]:
    return (report.get("counters") or {}).get(name, [])


def cache_stats(report: dict) -> dict[str, float]:
    by_result = {"hit": 0.0, "miss": 0.0, "empty": 0.0}
    for series in _counter_series(report, "makisu_cache_pull_total"):
        result = series.get("labels", {}).get("result", "")
        if result in by_result:
            by_result[result] += series.get("value", 0.0)
    lookups = by_result["hit"] + by_result["miss"]
    by_result["ratio"] = by_result["hit"] / lookups if lookups else 0.0
    return by_result


def bytes_hashed_by_backend(report: dict) -> dict[str, float]:
    out: dict[str, float] = {}
    for series in _counter_series(report, "makisu_bytes_hashed_total"):
        backend = series.get("labels", {}).get("backend", "?")
        out[backend] = out.get(backend, 0.0) + series.get("value", 0.0)
    return out


def commit_stage_busy(report: dict) -> dict[str, float]:
    """Busy seconds per layer-commit pipeline stage (tar_write,
    read_ahead, gear_scan, chunk_sha, compress) — the multicore
    commit's own breakdown. The busiest stage is the one to attack:
    it bounds commit throughput no matter how many workers the others
    get."""
    from makisu_tpu.utils import metrics
    out: dict[str, float] = {}
    for series in _counter_series(report, metrics.COMMIT_STAGE_BUSY):
        stage = series.get("labels", {}).get("stage", "?")
        out[stage] = out.get(stage, 0.0) + series.get("value", 0.0)
    return out


# -- cross-process fleet trace assembly ------------------------------------

FLEET_TRACE_SCHEMA = "makisu-tpu.fleet-trace.v1"


def assemble_fleet_trace(event_log: list[dict]) -> dict:
    """Reconstruct cross-process span trees from a merged event
    stream — the fleet front door's own span events plus the worker
    build events its forwarder tees back in (each tagged ``worker``).

    The stitch is structural, not heuristic: the worker adopted the
    front door's ``fleet_forward`` span as its registry root, so its
    top build span's ``parent_id`` IS that forward span's id — linking
    parents across processes builds one tree per trace id, failover
    attempts landing as sibling ``fleet_forward`` subtrees. Worker
    admission waits (``queue_wait`` events, stamped with the inbound
    trace ids) synthesize into spans so the front-door quota wait and
    the worker queue wait sit side by side on the timeline. Duplicate
    deliveries (an in-process fleet sees a worker's event both
    directly and via the tee) collapse by span id."""
    spans: dict[str, dict] = {}
    order: list[str] = []
    seen_waits: set[tuple] = set()
    seen_access: set[tuple] = set()
    wire: dict[str, dict[str, float]] = {}

    def note_wire(trace_id: str, kind: str, nbytes: float) -> None:
        per = wire.setdefault(trace_id or "?", {})
        per[kind] = per.get(kind, 0.0) + nbytes

    for ev in event_log:
        etype = ev.get("type")
        if etype == "span_start":
            sid = str(ev.get("span_id") or "")
            if not sid:
                continue
            if sid in spans:
                # Duplicate delivery: keep the first copy, but adopt
                # the worker tag if only the teed copy carries it.
                if ev.get("worker") and not spans[sid].get("source"):
                    spans[sid]["source"] = str(ev["worker"])
                continue
            span = {
                "name": str(ev.get("name", "?")),
                "span_id": sid,
                "parent_id": str(ev.get("parent_id") or ""),
                "trace_id": str(ev.get("trace_id") or ""),
                "start": float(ev.get("ts") or 0.0),
                "duration": None,
                "attrs": dict(ev.get("attrs") or {}),
                "children": [],
            }
            if ev.get("worker"):
                span["source"] = str(ev["worker"])
            spans[sid] = span
            order.append(sid)
        elif etype == "span_end":
            span = spans.get(str(ev.get("span_id") or ""))
            if span is not None and span["duration"] is None:
                span["duration"] = float(ev.get("duration") or 0.0)
                if ev.get("error"):
                    span["error"] = str(ev["error"])
        elif etype == "queue_wait":
            key = (ev.get("trace_id", ""), ev.get("parent_id", ""),
                   ev.get("ts", 0.0))
            if key in seen_waits:
                continue
            seen_waits.add(key)
            seconds = float(ev.get("seconds") or 0.0)
            end = float(ev.get("ts") or 0.0)
            sid = f"queue-wait-{len(seen_waits)}"
            span = {
                "name": "queue_wait",
                "span_id": sid,
                "parent_id": str(ev.get("parent_id") or ""),
                "trace_id": str(ev.get("trace_id") or ""),
                "start": end - seconds,
                "duration": seconds,
                "attrs": {"tenant": str(ev.get("tenant") or "")},
                "children": [],
            }
            if ev.get("worker"):
                span["source"] = str(ev["worker"])
            spans[sid] = span
            order.append(sid)
        elif etype == "serve_access":
            # An in-process fleet sees a worker's access row twice —
            # the direct emission and the shutdown ledger collection —
            # as byte-equal events (the AccessLog delivers the row
            # itself); dedupe on the row's identifying fields.
            key = (ev.get("ts"), ev.get("kind"), ev.get("name"),
                   ev.get("status"), ev.get("bytes"),
                   ev.get("trace_id"))
            if key in seen_access:
                continue
            seen_access.add(key)
            note_wire(str(ev.get("trace_id") or ""), "serve",
                      float(ev.get("bytes") or 0.0))
        elif etype == "registry_blob":
            note_wire("?", f"registry_{ev.get('direction', '?')}",
                      float(ev.get("bytes") or 0.0))

    # Trace ids flood down: a child span inherits its ancestors' trace
    # id when its own event predates adoption metadata (defensive —
    # span events all carry trace_id today).
    roots: list[dict] = []
    for sid in order:
        span = spans[sid]
        parent = spans.get(span["parent_id"])
        if parent is not None and parent is not span:
            if not span["trace_id"]:
                span["trace_id"] = parent["trace_id"]
            parent["children"].append(span)
        else:
            roots.append(span)
    for span in spans.values():
        span["children"].sort(key=lambda s: s["start"])

    by_trace: dict[str, list[dict]] = {}
    trace_order: list[str] = []
    for root in roots:
        tid = root["trace_id"] or "?"
        if tid not in by_trace:
            by_trace[tid] = []
            trace_order.append(tid)
        by_trace[tid].append(root)
    traces = []
    for tid in trace_order:
        tops = sorted(by_trace[tid], key=lambda s: s["start"])
        traces.append({
            "trace_id": tid,
            "spans": tops,
            "wire_bytes": {k: int(v) for k, v in
                           sorted(wire.get(tid, {}).items())},
        })
    shared_wire = {k: int(v) for k, v in sorted(wire.get("?",
                                                         {}).items())}
    return {
        "schema": FLEET_TRACE_SCHEMA,
        "traces": traces,
        "span_count": len(spans),
        "untraced_wire_bytes": shared_wire,
    }


def fleet_perfetto_trace(assembled: dict) -> dict:
    """Chrome trace-event JSON of an assembled fleet trace: one
    Perfetto PROCESS track per source — the front door plus each
    worker — so the cross-process handoff (forward span here, build
    span there) reads as a fleet, not a flattened single track."""
    pids: dict[str, int] = {"frontdoor": 1}
    meta: list[dict] = [
        {"name": "process_name", "ph": "M", "pid": 1,
         "args": {"name": "makisu-tpu fleet front door"}},
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
         "args": {"name": "route"}},
    ]
    slices: list[dict] = []

    def pid_of(source: str) -> int:
        if source not in pids:
            pids[source] = len(pids) + 1
            meta.append({"name": "process_name", "ph": "M",
                         "pid": pids[source],
                         "args": {"name": f"worker {source}"}})
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": pids[source], "tid": 1,
                         "args": {"name": "build"}})
        return pids[source]

    for trace in assembled.get("traces", []):
        for top in trace.get("spans", []):
            for span, _depth in _walk(top):
                event = {
                    "name": span.get("name", "?"),
                    "ph": "X",
                    "ts": round(float(span.get("start", 0.0)) * 1e6,
                                3),
                    "dur": round(_duration(span) * 1e6, 3),
                    "pid": pid_of(span.get("source", "frontdoor")),
                    "tid": 1,
                    "cat": phase_of(span.get("name", "")),
                    "args": {"trace_id": trace.get("trace_id", "")},
                }
                if span.get("span_id"):
                    event["args"]["span_id"] = span["span_id"]
                if span.get("parent_id"):
                    event["args"]["parent_id"] = span["parent_id"]
                if span.get("attrs"):
                    event["args"].update(span["attrs"])
                if span.get("error"):
                    event["args"]["error"] = span["error"]
                slices.append(event)
    return {
        "traceEvents": meta + slices,
        "displayTimeUnit": "ms",
        "otherData": {
            "schema": assembled.get("schema", FLEET_TRACE_SCHEMA),
            "traces": [t.get("trace_id", "")
                       for t in assembled.get("traces", [])],
        },
    }


def _find_spans(top: dict, name: str) -> list[dict]:
    return [span for span, _ in _walk(top)
            if span.get("name") == name]


def render_fleet_report(assembled: dict, profile: dict | None = None) -> str:
    """The ``makisu-tpu report --fleet`` output: per trace, the
    cross-process critical path (whose total is the front door's wall
    time — the root IS the fleet_build span), the admission economics
    side by side (front-door quota wait vs worker queue wait), per-
    attempt routing (failover attempts as sibling subtrees), build
    phase self-times, and bytes on wire. ``profile`` (a merged
    ``makisu-tpu.profile.v1`` document, e.g. from ``profile --fleet
    --out``) appends the sampled where-did-the-cycles-go view beside
    the span-declared one."""
    traces = assembled.get("traces", [])
    lines = [f"makisu-tpu fleet trace report — {len(traces)} "
             f"trace(s), {assembled.get('span_count', 0)} span(s)"]
    for trace in traces:
        report_shape = {"spans": trace.get("spans", []),
                        "trace_id": trace.get("trace_id", "")}
        top = root_span(report_shape)
        if top is None:
            continue
        total = _duration(top)
        lines.append("")
        lines.append(f"trace {trace.get('trace_id', '?')} — "
                     f"{top.get('name', '?')}  wall {total:.3f}s")
        # Admission economics: the front door's quota wait vs the
        # worker's admission-queue wait, side by side.
        quota = sum(_duration(s)
                    for s in _find_spans(top, "fleet_admit"))
        queue = sum(_duration(s)
                    for s in _find_spans(top, "queue_wait"))
        lines.append(f"  front-door quota wait {quota:.3f}s   "
                     f"worker queue wait {queue:.3f}s")
        # Per-attempt routing: each fleet_forward subtree is one
        # attempt; >1 means failover happened inside this ONE trace.
        forwards = _find_spans(top, "fleet_forward")
        for f in sorted(forwards,
                        key=lambda s: int(s.get("attrs", {})
                                          .get("attempt", 0))):
            attrs = f.get("attrs", {})
            outcome = "failed" if f.get("error") else "ok"
            built = any(s.get("source") for s, _ in _walk(f)
                        if s is not f)
            lines.append(
                f"  attempt {attrs.get('attempt', '?')}: worker "
                f"{attrs.get('worker', '?')} ({attrs.get('verdict', '?')})"
                f"  {_duration(f):.3f}s  "
                f"{'built' if built else outcome}")
        phases = phase_totals(report_shape)
        lines.append("  build phases (self time): " + "  ".join(
            f"{phase}={phases[phase]:.3f}s" for phase in PHASES))
        wire = trace.get("wire_bytes", {})
        if wire:
            lines.append("  bytes on wire: " + "  ".join(
                f"{kind}={fmt_bytes(n)}"
                for kind, n in sorted(wire.items())))
        path = critical_path(report_shape)
        lines.append(f"  critical path (longest chain, total "
                     f"{total:.3f}s):")
        for hop in path:
            pct = 100.0 * hop["duration"] / total if total else 0.0
            attrs = hop["attrs"]
            label = hop["name"]
            detail = ", ".join(f"{k}={v}"
                               for k, v in sorted(attrs.items()))
            if detail:
                label += f" [{detail}]"
            indent = "  " * hop["depth"]
            lines.append(
                f"    {indent}{label:<40s} {hop['duration']:9.3f}s "
                f"{pct:5.1f}%  (self {hop['self']:.3f}s)")
    untraced = assembled.get("untraced_wire_bytes", {})
    if untraced:
        lines.append("")
        lines.append("untraced wire bytes: " + "  ".join(
            f"{kind}={fmt_bytes(n)}"
            for kind, n in sorted(untraced.items())))
    if profile and profile.get("samples"):
        from makisu_tpu.utils import profiler
        total = profile["samples"]
        workers = profile.get("workers") or {}
        lines.append("")
        lines.append(
            f"fleet profile: {total} samples"
            + (f" across {len(workers)} worker(s)" if workers else "")
            + f", sampler overhead "
              f"{100.0 * profile.get('overhead_fraction', 0.0):.2f}%")
        phases = profile.get("phases") or {}
        if phases:
            lines.append("  sampled phase shares: " + "  ".join(
                f"{phase}={100.0 * phases.get(phase, 0) / total:.1f}%"
                for phase in PHASES if phases.get(phase)))
        for phase in sorted(phases):
            hot = profiler.dominant_frame(profile, phase)
            if hot:
                lines.append(f"  {phase:<6s} hottest frame {hot[0]} "
                             f"({hot[1]} samples)")
    return "\n".join(lines) + "\n"


# -- the `makisu-tpu report` text ------------------------------------------


def fmt_bytes(n: float) -> str:
    """Human byte count; shared by this report and `doctor`
    (utils/flightrecorder.py) so the two outputs can't drift."""
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return (f"{n:.1f}{unit}" if unit != "B"
                    else f"{int(n)}{unit}")
        n /= 1024
    return f"{n:.1f}GiB"  # pragma: no cover - loop always returns


_fmt_bytes = fmt_bytes  # internal call sites predate the public name


def render_report(report: dict, event_log: list[dict] | None = None,
                  capture_ts: float | None = None) -> str:
    """The ``makisu-tpu report`` output: critical path, phase
    breakdown, top time sinks, cache/hashing counters, resource usage
    per phase (when the sampler ran), and (with an event log) an
    event-type census. Handles a build that died mid-flight: open
    spans (null durations) are listed and marked, completed spans
    still get phase self-times, and ``capture_ts`` (a bundle's capture
    moment) substitutes for the missing root wall time."""
    lines: list[str] = []
    top = root_span(report)
    command = report.get("command") or (top or {}).get("name") or "?"
    lines.append(f"makisu-tpu build report — command: {command}")
    if report.get("trace_id"):
        lines.append(f"trace id: {report['trace_id']}")
    if top is None:
        lines.append("no spans recorded (empty report)")
        return "\n".join(lines) + "\n"
    total = _duration(top)
    died_open = top.get("duration") is None
    if died_open and capture_ts:
        total = max(capture_ts - float(top.get("start", capture_ts)), 0.0)
    lines.append(f"wall time: {total:.3f}s"
                 + ("  (build died mid-flight; root span never closed)"
                    if died_open else "")
                 + (f"  exit code: {report['exit_code']}"
                    if "exit_code" in report else ""))

    path = critical_path(report)
    lines.append("")
    lines.append(f"critical path (longest span chain, "
                 f"total {total:.3f}s):")
    for hop in path:
        pct = 100.0 * hop["duration"] / total if total else 0.0
        attrs = hop["attrs"]
        label = hop["name"]
        detail = ", ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        if detail:
            label += f" [{detail}]"
        indent = "  " * hop["depth"]
        lines.append(f"  {indent}{label:<40s} {hop['duration']:9.3f}s "
                     f"{pct:5.1f}%  (self {hop['self']:.3f}s)")

    open_spans = open_spans_in(report)
    if open_spans:
        lines.append("")
        lines.append(f"spans still open at capture ({len(open_spans)}) "
                     "— where the build was when it died:")
        for span in open_spans:
            detail = ", ".join(f"{k}={v}" for k, v in
                               sorted(span["attrs"].items()))
            label = span["name"] + (f" [{detail}]" if detail else "")
            indent = "  " * span["depth"]
            age = ""
            if capture_ts:
                age = f"  open {max(capture_ts - span['start'], 0.0):.1f}s"
            lines.append(f"  {indent}{label:<40s} ✱ open{age}")

    phases = phase_totals(report)
    lines.append("")
    lines.append("phase breakdown (self time, completed spans): "
                 + "  ".join(
                     f"{phase}={phases[phase]:.3f}s" for phase in PHASES))

    resources = resources_by_phase(report)
    if resources:
        lines.append("")
        lines.append("resource usage by phase (sampled):")
        for phase in PHASES:
            agg = resources.get(phase)
            if not agg:
                continue
            lines.append(
                f"  {phase:<6s} peak rss "
                f"{_fmt_bytes(agg['peak_rss_bytes']):>10s}   cpu "
                f"{agg['cpu_seconds']:8.3f}s")

    sinks = sorted(self_time_by_name(report).items(),
                   key=lambda kv: kv[1], reverse=True)[:5]
    lines.append("")
    lines.append("top time sinks (self time):")
    for name, self_t in sinks:
        pct = 100.0 * self_t / total if total else 0.0
        lines.append(f"  {name:<28s} {phase_of(name):<6s} "
                     f"{self_t:9.3f}s {pct:5.1f}%")

    cache = cache_stats(report)
    lines.append("")
    lines.append(f"cache: {int(cache['hit'])} hit / "
                 f"{int(cache['miss'])} miss / "
                 f"{int(cache['empty'])} empty  "
                 f"(hit ratio {100.0 * cache['ratio']:.1f}%)")

    hashed = bytes_hashed_by_backend(report)
    if hashed:
        per_backend = "  ".join(
            f"{backend}={_fmt_bytes(n)}"
            for backend, n in sorted(hashed.items()))
        lines.append(f"bytes hashed: {per_backend}"
                     + (f"  ({_fmt_bytes(sum(hashed.values()) / total)}/s)"
                        if total else ""))
    else:
        lines.append("bytes hashed: none recorded")

    stages = commit_stage_busy(report)
    if stages:
        lines.append("")
        lines.append("commit pipeline stages (busy time):")
        ordered = sorted(stages.items(), key=lambda kv: kv[1],
                         reverse=True)
        for i, (stage, busy) in enumerate(ordered):
            lines.append(f"  {stage:<12s} {busy:9.3f}s"
                         + ("  ← bottleneck" if i == 0 and busy else ""))

    if event_log is not None:
        census: dict[str, int] = {}
        for event in event_log:
            census[event.get("type", "?")] = \
                census.get(event.get("type", "?"), 0) + 1
        lines.append("")
        lines.append(f"event log: {len(event_log)} events  " + "  ".join(
            f"{t}={n}" for t, n in sorted(census.items())))
    return "\n".join(lines) + "\n"
