"""Trace export and critical-path analysis of build telemetry reports.

Input everywhere is the ``--metrics-out`` report dict
(``metrics.MetricsRegistry.report()`` plus CLI extras): span tree,
counters, trace id. Two consumers:

- :func:`perfetto_trace` renders the span tree as Chrome/Perfetto
  trace-event JSON (the ``--trace-out`` file): complete ("X") events
  with microsecond timestamps, loadable in ui.perfetto.dev or
  chrome://tracing.
- :func:`render_report` is the ``makisu-tpu report`` subcommand's
  output: the longest span chain (the critical path through the nested
  timing tree — what to attack first to make the build faster), the
  top self-time sinks grouped into pull/chunk/hash/push phases, cache
  hit ratio, and bytes hashed per backend.

Self-time is a span's duration minus its children's — the time the
span itself burned. Summed over the tree it reconstructs the root's
wall time (concurrent children can push a span's child-sum past its
own duration; self-time floors at zero so aggregates stay sane).
"""

from __future__ import annotations

from typing import Any, Iterator

# Span-name substrings -> build phase, first match wins. Order matters:
# "pull_cache_layers" must classify as pull before "cache" could ever
# grow a phase of its own, and commit/hash both land in hash (layer
# commit IS the hashing path).
_PHASE_RULES: tuple[tuple[str, str], ...] = (
    ("pull", "pull"),
    ("from", "pull"),
    ("chunk", "chunk"),
    ("hash", "hash"),
    ("commit", "hash"),
    ("push", "push"),
)

PHASES = ("pull", "chunk", "hash", "push", "other")


def phase_of(span_name: str) -> str:
    name = span_name.lower()
    for needle, phase in _PHASE_RULES:
        if needle in name:
            return phase
    return "other"


def _walk(span: dict, depth: int = 0) -> Iterator[tuple[dict, int]]:
    yield span, depth
    for child in span.get("children", []):
        yield from _walk(child, depth + 1)


def _duration(span: dict) -> float:
    # Open spans (process died mid-span) carry null; treat as zero so
    # analysis of a torn report still works.
    return float(span.get("duration") or 0.0)


def root_span(report: dict) -> dict | None:
    """The invocation's top span (reports hold one top-level span per
    command; if several exist, the longest wins)."""
    spans = report.get("spans") or []
    if not spans:
        return None
    return max(spans, key=_duration)


# -- Perfetto / Chrome trace-event export ----------------------------------


def perfetto_trace(report: dict) -> dict:
    """Chrome trace-event JSON (the subset Perfetto loads) from a
    report's span tree. One complete ("X") slice per span; nesting
    falls out of timestamp containment on a single track. Span/trace
    ids and attrs ride in ``args`` so slices link back to event-log
    lines and server-side traceparent correlation."""
    trace_id = report.get("trace_id", "")
    slices: list[dict] = []
    for top in report.get("spans") or []:
        for span, _depth in _walk(top):
            event = {
                "name": span.get("name", "?"),
                "ph": "X",
                "ts": round(float(span.get("start", 0.0)) * 1e6, 3),
                "dur": round(_duration(span) * 1e6, 3),
                "pid": 1,
                "tid": 1,
                "cat": phase_of(span.get("name", "")),
                "args": {},
            }
            if span.get("span_id"):
                event["args"]["span_id"] = span["span_id"]
            if span.get("parent_id"):
                event["args"]["parent_id"] = span["parent_id"]
            if span.get("attrs"):
                event["args"].update(span["attrs"])
            if span.get("error"):
                event["args"]["error"] = span["error"]
            slices.append(event)
    out = {
        "traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 1,
             "args": {"name": f"makisu-tpu {report.get('command', '')}"
                      .strip()}},
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
             "args": {"name": "build"}},
            *slices,
        ],
        "displayTimeUnit": "ms",
    }
    if trace_id:
        out["otherData"] = {"trace_id": trace_id}
    return out


# -- critical path ---------------------------------------------------------


def critical_path(report: dict) -> list[dict]:
    """The longest span chain root→leaf: from each span, descend into
    the child that consumed the most wall time. Returns hops as
    ``{"name", "duration", "self", "depth", "attrs"}``; the first hop
    is the root, so the path's total IS the root's wall time — the
    chain tells you where that time concentrates."""
    top = root_span(report)
    if top is None:
        return []
    path: list[dict] = []
    span, depth = top, 0
    while span is not None:
        children = span.get("children", [])
        child_sum = sum(_duration(c) for c in children)
        path.append({
            "name": span.get("name", "?"),
            "duration": _duration(span),
            "self": max(_duration(span) - child_sum, 0.0),
            "depth": depth,
            "attrs": span.get("attrs", {}),
        })
        span = max(children, key=_duration) if children else None
        depth += 1
    return path


def self_time_by_name(report: dict) -> dict[str, float]:
    """Aggregate self-time per span name across the whole tree."""
    out: dict[str, float] = {}
    for top in report.get("spans") or []:
        for span, _depth in _walk(top):
            child_sum = sum(_duration(c)
                            for c in span.get("children", []))
            self_t = max(_duration(span) - child_sum, 0.0)
            name = span.get("name", "?")
            out[name] = out.get(name, 0.0) + self_t
    return out


def phase_totals(report: dict) -> dict[str, float]:
    """Self-time per build phase (pull/chunk/hash/push/other)."""
    totals = {phase: 0.0 for phase in PHASES}
    for name, self_t in self_time_by_name(report).items():
        totals[phase_of(name)] += self_t
    return totals


def open_spans_in(report: dict) -> list[dict]:
    """Spans with a null duration — open when the report was captured,
    i.e. the process died (or was snapshotted) mid-span. A finished
    build's report has none; a flight-recorder bundle's metrics
    snapshot typically has the whole stuck chain."""
    out = []
    for top in report.get("spans") or []:
        for span, depth in _walk(top):
            if span.get("duration") is None:
                out.append({
                    "name": span.get("name", "?"),
                    "depth": depth,
                    "start": float(span.get("start", 0.0)),
                    "attrs": span.get("attrs", {}),
                })
    return out


def resources_by_phase(report: dict) -> dict[str, dict[str, float]]:
    """Peak RSS and CPU seconds per build phase, from the per-span
    resource attribution the sampler recorded (utils/resources.py).
    Peak RSS is a max (it is a process-wide level observed while the
    span was open); CPU sums the per-leaf charges, so phases are
    roughly exclusive."""
    out: dict[str, dict[str, float]] = {}
    for top in report.get("spans") or []:
        for span, _depth in _walk(top):
            res = span.get("resources")
            if not res:
                continue
            phase = phase_of(span.get("name", ""))
            agg = out.setdefault(phase,
                                 {"peak_rss_bytes": 0.0,
                                  "cpu_seconds": 0.0})
            agg["peak_rss_bytes"] = max(agg["peak_rss_bytes"],
                                        float(res.get("peak_rss_bytes",
                                                      0)))
            agg["cpu_seconds"] += float(res.get("cpu_seconds", 0.0))
    return out


# -- counters --------------------------------------------------------------


def _counter_series(report: dict, name: str) -> list[dict]:
    return (report.get("counters") or {}).get(name, [])


def cache_stats(report: dict) -> dict[str, float]:
    by_result = {"hit": 0.0, "miss": 0.0, "empty": 0.0}
    for series in _counter_series(report, "makisu_cache_pull_total"):
        result = series.get("labels", {}).get("result", "")
        if result in by_result:
            by_result[result] += series.get("value", 0.0)
    lookups = by_result["hit"] + by_result["miss"]
    by_result["ratio"] = by_result["hit"] / lookups if lookups else 0.0
    return by_result


def bytes_hashed_by_backend(report: dict) -> dict[str, float]:
    out: dict[str, float] = {}
    for series in _counter_series(report, "makisu_bytes_hashed_total"):
        backend = series.get("labels", {}).get("backend", "?")
        out[backend] = out.get(backend, 0.0) + series.get("value", 0.0)
    return out


def commit_stage_busy(report: dict) -> dict[str, float]:
    """Busy seconds per layer-commit pipeline stage (tar_write,
    read_ahead, gear_scan, chunk_sha, compress) — the multicore
    commit's own breakdown. The busiest stage is the one to attack:
    it bounds commit throughput no matter how many workers the others
    get."""
    from makisu_tpu.utils import metrics
    out: dict[str, float] = {}
    for series in _counter_series(report, metrics.COMMIT_STAGE_BUSY):
        stage = series.get("labels", {}).get("stage", "?")
        out[stage] = out.get(stage, 0.0) + series.get("value", 0.0)
    return out


# -- the `makisu-tpu report` text ------------------------------------------


def fmt_bytes(n: float) -> str:
    """Human byte count; shared by this report and `doctor`
    (utils/flightrecorder.py) so the two outputs can't drift."""
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return (f"{n:.1f}{unit}" if unit != "B"
                    else f"{int(n)}{unit}")
        n /= 1024
    return f"{n:.1f}GiB"  # pragma: no cover - loop always returns


_fmt_bytes = fmt_bytes  # internal call sites predate the public name


def render_report(report: dict, event_log: list[dict] | None = None,
                  capture_ts: float | None = None) -> str:
    """The ``makisu-tpu report`` output: critical path, phase
    breakdown, top time sinks, cache/hashing counters, resource usage
    per phase (when the sampler ran), and (with an event log) an
    event-type census. Handles a build that died mid-flight: open
    spans (null durations) are listed and marked, completed spans
    still get phase self-times, and ``capture_ts`` (a bundle's capture
    moment) substitutes for the missing root wall time."""
    lines: list[str] = []
    top = root_span(report)
    command = report.get("command") or (top or {}).get("name") or "?"
    lines.append(f"makisu-tpu build report — command: {command}")
    if report.get("trace_id"):
        lines.append(f"trace id: {report['trace_id']}")
    if top is None:
        lines.append("no spans recorded (empty report)")
        return "\n".join(lines) + "\n"
    total = _duration(top)
    died_open = top.get("duration") is None
    if died_open and capture_ts:
        total = max(capture_ts - float(top.get("start", capture_ts)), 0.0)
    lines.append(f"wall time: {total:.3f}s"
                 + ("  (build died mid-flight; root span never closed)"
                    if died_open else "")
                 + (f"  exit code: {report['exit_code']}"
                    if "exit_code" in report else ""))

    path = critical_path(report)
    lines.append("")
    lines.append(f"critical path (longest span chain, "
                 f"total {total:.3f}s):")
    for hop in path:
        pct = 100.0 * hop["duration"] / total if total else 0.0
        attrs = hop["attrs"]
        label = hop["name"]
        detail = ", ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        if detail:
            label += f" [{detail}]"
        indent = "  " * hop["depth"]
        lines.append(f"  {indent}{label:<40s} {hop['duration']:9.3f}s "
                     f"{pct:5.1f}%  (self {hop['self']:.3f}s)")

    open_spans = open_spans_in(report)
    if open_spans:
        lines.append("")
        lines.append(f"spans still open at capture ({len(open_spans)}) "
                     "— where the build was when it died:")
        for span in open_spans:
            detail = ", ".join(f"{k}={v}" for k, v in
                               sorted(span["attrs"].items()))
            label = span["name"] + (f" [{detail}]" if detail else "")
            indent = "  " * span["depth"]
            age = ""
            if capture_ts:
                age = f"  open {max(capture_ts - span['start'], 0.0):.1f}s"
            lines.append(f"  {indent}{label:<40s} ✱ open{age}")

    phases = phase_totals(report)
    lines.append("")
    lines.append("phase breakdown (self time, completed spans): "
                 + "  ".join(
                     f"{phase}={phases[phase]:.3f}s" for phase in PHASES))

    resources = resources_by_phase(report)
    if resources:
        lines.append("")
        lines.append("resource usage by phase (sampled):")
        for phase in PHASES:
            agg = resources.get(phase)
            if not agg:
                continue
            lines.append(
                f"  {phase:<6s} peak rss "
                f"{_fmt_bytes(agg['peak_rss_bytes']):>10s}   cpu "
                f"{agg['cpu_seconds']:8.3f}s")

    sinks = sorted(self_time_by_name(report).items(),
                   key=lambda kv: kv[1], reverse=True)[:5]
    lines.append("")
    lines.append("top time sinks (self time):")
    for name, self_t in sinks:
        pct = 100.0 * self_t / total if total else 0.0
        lines.append(f"  {name:<28s} {phase_of(name):<6s} "
                     f"{self_t:9.3f}s {pct:5.1f}%")

    cache = cache_stats(report)
    lines.append("")
    lines.append(f"cache: {int(cache['hit'])} hit / "
                 f"{int(cache['miss'])} miss / "
                 f"{int(cache['empty'])} empty  "
                 f"(hit ratio {100.0 * cache['ratio']:.1f}%)")

    hashed = bytes_hashed_by_backend(report)
    if hashed:
        per_backend = "  ".join(
            f"{backend}={_fmt_bytes(n)}"
            for backend, n in sorted(hashed.items()))
        lines.append(f"bytes hashed: {per_backend}"
                     + (f"  ({_fmt_bytes(sum(hashed.values()) / total)}/s)"
                        if total else ""))
    else:
        lines.append("bytes hashed: none recorded")

    stages = commit_stage_busy(report)
    if stages:
        lines.append("")
        lines.append("commit pipeline stages (busy time):")
        ordered = sorted(stages.items(), key=lambda kv: kv[1],
                         reverse=True)
        for i, (stage, busy) in enumerate(ordered):
            lines.append(f"  {stage:<12s} {busy:9.3f}s"
                         + ("  ← bottleneck" if i == 0 and busy else ""))

    if event_log is not None:
        census: dict[str, int] = {}
        for event in event_log:
            census[event.get("type", "?")] = \
                census.get(event.get("type", "?"), 0) + 1
        lines.append("")
        lines.append(f"event log: {len(event_log)} events  " + "  ".join(
            f"{t}={n}" for t, n in sorted(census.items())))
    return "\n".join(lines) + "\n"
