"""Build-farm front door: fleet scheduler + worker registry + peers.

ROADMAP item 1. The worker (makisu_tpu/worker/) stayed one process for
ten PRs; this package turns N of them into a fleet:

- ``scheduler.py`` — the routing core: session-affinity first (a build
  landing on the worker holding its resident session gets the ~1.15s
  warm rebuild; anywhere else pays the cold path), consistent-hash
  placement for new contexts, least-loaded spillover past a queue-depth
  threshold, per-tenant in-flight quotas, and failover when a worker is
  unreachable or refuses admission.
- ``server.py`` — the HTTP front door. It speaks the worker's own
  protocol over a unix socket, so every existing client (WorkerClient,
  ``makisu-tpu top``, loadgen) points at the fleet socket unchanged.
- ``peers.py`` — the peer chunk-exchange map the scheduler publishes:
  a worker missing a chunk consults its peers' ``GET /chunks/<fp>``
  (budget-charged through the transfer engine) before paying the
  registry. Deliberately minimal — the blob-CAS/chunk-CAS/pack
  content-store unification is its own future PR (ROADMAP).
- ``kv.py`` — a shared cache-KV endpoint (the HTTPStore wire protocol)
  for fleet harnesses: loadgen/CI give every worker one cache plane so
  cross-worker cache hits (and therefore peer chunk fetches) are real.
"""

from makisu_tpu.fleet.scheduler import FleetScheduler, WorkerSpec
from makisu_tpu.fleet.server import FleetServer

__all__ = ["FleetScheduler", "FleetServer", "WorkerSpec"]
