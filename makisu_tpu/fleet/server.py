"""Fleet front door: the worker protocol, fronting N workers.

The server speaks the same unix-socket HTTP surface as a single worker
(``/ready``, ``/build``, ``/healthz``, ``/builds``, ``/metrics``,
``/exit``), so every existing consumer — :class:`WorkerClient`,
``makisu-tpu top``, loadgen, CI scripts — points at the fleet socket
unchanged. On top of that it adds the fleet-only surface:

- ``GET /fleet`` — the scheduler's full routing table: per-worker
  state, sticky placements, tenant quotas, recent decisions.
- ``GET /peers`` — the current peer map (also pushed to workers).
- ``POST /drain`` — ``{"worker": ID[, "undrain": true]}``: graceful
  drain (new builds route elsewhere; the worker stays up serving its
  in-flight builds and peer chunk fetches).

``POST /build`` is the routing path: admission (tenant quota +
fleet-wide cap) at the front door, then route → forward → stream the
worker's NDJSON frames through verbatim. The terminal frame is
augmented with ``worker``, ``fleet_verdict``, ``fleet_attempts`` and
``quota_wait_seconds`` so clients (and loadgen's fleet report) never
parse logs for routing outcomes. A worker that is unreachable, refuses
admission (the no-wait 503), or dies mid-stream is excluded and the
build retries on the next-best worker — log frames already forwarded
are not un-sent (duplicated lines are the documented cost of a
mid-stream failover; the terminal frame is emitted exactly once).
"""

from __future__ import annotations

import collections
import json
import os
import socketserver
import threading
import time
from http.server import BaseHTTPRequestHandler

from makisu_tpu.fleet import slo as slo_mod
from makisu_tpu.fleet.scheduler import (
    FleetScheduler,
    NoWorkersError,
    WorkerSpec,
    build_identity,
)
from makisu_tpu.utils import events
from makisu_tpu.utils import logging as log
from makisu_tpu.utils import metrics

# Attempts per build across distinct workers (initial + failovers).
MAX_ATTEMPTS = 3

# Read timeout for one worker's build stream: frames are heartbeat-ish
# (logs, events); a worker silent this long is wedged and the build is
# better restarted elsewhere. Generous — a 100k-file commit can be
# quiet for a while between frames.
STREAM_READ_TIMEOUT = 900.0

_LATENCY_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                    120.0, 300.0, 600.0, 1800.0)


def rewrite_storage(argv: list[str], storage: str) -> list[str]:
    """Rewrite/append ``--storage`` so the build lands on the routed
    worker's own storage (the per-worker override an in-process fleet
    uses to model per-machine disks). Handles both ``--storage PATH``
    and ``--storage=PATH`` spellings."""
    out = list(argv)
    for i, arg in enumerate(out):
        if arg == "--storage" and i + 1 < len(out):
            out[i + 1] = storage
            return out
        if arg.startswith("--storage="):
            out[i] = f"--storage={storage}"
            return out
    return out + ["--storage", storage]


class _FleetHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *args) -> None:  # quiet
        pass

    # -- GET ---------------------------------------------------------------

    def do_GET(self) -> None:
        server: FleetServer = self.server
        if self.path == "/ready":
            ok = any(w.alive for w in
                     server.scheduler.workers.values())
            self._respond(200 if ok else 503,
                          b"ok" if ok else b"no workers alive")
        elif self.path == "/metrics":
            # Aggregated scrape: the front door's own series plus every
            # alive worker's re-exported under a worker="wN" label —
            # one Prometheus target sees the whole fleet.
            self._respond(
                200, server.aggregated_metrics().encode(),
                content_type="text/plain; version=0.0.4; "
                             "charset=utf-8")
        elif self.path == "/healthz":
            self._respond(200, json.dumps(server.health()).encode(),
                          content_type="application/json")
        elif self.path == "/builds":
            self._respond(200, json.dumps(server.builds()).encode(),
                          content_type="application/json")
        elif self.path == "/fleet":
            self._respond(200,
                          json.dumps(server.scheduler.stats()).encode(),
                          content_type="application/json")
        elif self.path == "/peers":
            stats = server.scheduler.stats()
            self._respond(200, json.dumps({
                "version": stats["peer_map_version"],
                "peers": [w["socket"] for w in stats["workers"]
                          if w["alive"]],
            }).encode(), content_type="application/json")
        elif self.path == "/alerts":
            self._respond(200, json.dumps(server.alerts()).encode(),
                          content_type="application/json")
        elif self.path == "/profile" or self.path.startswith("/profile?"):
            # Fleet-wide on-demand profiling: every alive worker
            # captures a ?seconds=N window in parallel and the merged
            # makisu-tpu.profile.v1 comes back — one request answers
            # "where is the FLEET's time going right now".
            from urllib.parse import parse_qs, urlsplit
            query = parse_qs(urlsplit(self.path).query)
            try:
                seconds = float((query.get("seconds") or ["5"])[0])
            except ValueError:
                self._respond(400, b"bad seconds")
                return
            self._respond(200,
                          json.dumps(server.profile(seconds)).encode(),
                          content_type="application/json")
        elif self.path == "/exit":
            threading.Thread(target=server.shutdown,
                             daemon=True).start()
            self._respond(200, b"bye")
        else:
            self._respond(404, b"not found")

    # -- POST --------------------------------------------------------------

    def do_POST(self) -> None:
        if self.path == "/drain":
            self._handle_drain()
        elif self.path == "/build":
            self._handle_build()
        else:
            self._respond(404, b"not found")

    def _handle_drain(self) -> None:
        length = int(self.headers.get("Content-Length", "0"))
        try:
            body = json.loads(self.rfile.read(length)) or {}
            worker_id = str(body["worker"])
            draining = not body.get("undrain", False)
        except (ValueError, KeyError, TypeError):
            self._respond(400, b'bad drain json (need {"worker": ID})')
            return
        if not self.server.scheduler.drain(worker_id, draining):
            self._respond(404, b"unknown worker")
            return
        snapshotted = 0
        if draining:
            # Checkpoint the draining worker's resident sessions into
            # the snapshot plane NOW: its contexts re-route on the next
            # build, and the prewarm path needs recipes to pull. Best-
            # effort — a worker that can't answer is already the case
            # drain exists for.
            snapshotted = self.server.checkpoint_worker(worker_id)
        self._respond(200, json.dumps(
            {"worker": worker_id, "draining": draining,
             "sessions_snapshotted": snapshotted}).encode(),
            content_type="application/json")

    def _handle_build(self) -> None:
        server: FleetServer = self.server
        length = int(self.headers.get("Content-Length", "0"))
        try:
            body = json.loads(self.rfile.read(length))
        except ValueError:
            self._respond(400, b"bad argv json")
            return
        tenant = ""
        traceparent = ""
        if isinstance(body, dict):
            argv = body.get("argv") or []
            tenant = str(body.get("tenant") or "")
            traceparent = str(body.get("traceparent") or "")
        else:
            argv = body
        tenant = self.headers.get("X-Makisu-Tenant") or tenant
        # The submitting client's trace context (header wins, like the
        # tenant): the front door ADOPTS it for this build's admit/
        # route/forward spans and hands its forward span down to the
        # worker — one trace id, front door to chunk wire.
        traceparent = self.headers.get("traceparent") or traceparent
        if not isinstance(argv, list) or not all(
                isinstance(a, str) for a in argv):
            self._respond(400, b"bad argv json")
            return

        self.send_response(200)
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        emit_lock = threading.Lock()
        finished = threading.Event()

        def emit(line: str) -> None:
            data = (line.rstrip("\n") + "\n").encode()
            frame = f"{len(data):x}\r\n".encode() + data + b"\r\n"
            with emit_lock:
                if finished.is_set():
                    return
                try:
                    self.wfile.write(frame)
                except (BrokenPipeError, ConnectionResetError):
                    finished.set()  # client gone; keep the build going

        try:
            server.route_build(argv, tenant, emit,
                               traceparent=traceparent)
        finally:
            with emit_lock:
                if not finished.is_set():
                    finished.set()
                    try:
                        self.wfile.write(b"0\r\n\r\n")
                    except (BrokenPipeError, ConnectionResetError):
                        pass

    def _respond(self, status: int, body: bytes,
                 content_type: str | None = None) -> None:
        try:
            self.send_response(status)
            if content_type:
                self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass


class FleetServer(socketserver.ThreadingMixIn,
                  socketserver.UnixStreamServer):
    """The front door process: HTTP surface + scheduler + forwarder."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, socket_path: str, specs: list[WorkerSpec],
                 poll_interval: float = 1.0,
                 tenant_quota: int = 0,
                 max_inflight: int = 0,
                 spillover_queue_depth: int = 2,
                 max_attempts: int = MAX_ATTEMPTS,
                 stall_window: float | None = None,
                 diag_out: str = "",
                 slo_config: str = "",
                 alert_webhook: str = "",
                 slo_interval: float | None = None,
                 canary_interval: float = 0.0,
                 canary_slow_seconds: float = 10.0) -> None:
        if os.path.exists(socket_path):
            os.unlink(socket_path)
        super().__init__(socket_path, _FleetHandler)
        self.socket_path = socket_path
        self.max_attempts = max(int(max_attempts), 1)
        self.scheduler = FleetScheduler(
            specs, poll_interval=poll_interval,
            tenant_quota=tenant_quota, max_inflight=max_inflight,
            spillover_queue_depth=spillover_queue_depth)
        self._started_mono = time.monotonic()
        self._mu = threading.Lock()
        self._seq = 0
        self._pending: dict[int, dict] = {}
        self._done_ok = 0
        self._done_failed = 0
        self._latencies: collections.deque[float] = collections.deque(
            maxlen=512)
        # Failure forensics, at parity with WorkerServer: a process
        # flight recorder sees every routed build's spans and every
        # teed worker event (global sink), and an optional stall
        # watchdog — gated on in-flight forwarded builds — dumps a
        # bundle when the front door stops making progress mid-route.
        from makisu_tpu.utils import flightrecorder, resources
        resources.ensure_started()
        self.recorder = flightrecorder.FlightRecorder()
        self._recorder_sink = self.recorder.record_event
        events.add_global_sink(self._recorder_sink)
        # Merged-trace collector: every event this process sees — the
        # front door's own admit/route/forward spans AND the worker
        # build events the forwarder tees back in — in one bounded
        # ring, the input `--trace-out` assembles into the merged
        # Perfetto export at shutdown.
        self._trace_events: collections.deque[dict] = \
            collections.deque(maxlen=65536)
        self._collector_sink = self._trace_events.append
        events.add_global_sink(self._collector_sink)
        self._watchdog = None
        if stall_window is None:
            stall_window = flightrecorder.stall_timeout_from_env()
        if stall_window > 0:
            self._watchdog = flightrecorder.StallWatchdog(
                stall_window, self.recorder,
                flightrecorder.forced_bundle_path(diag_out, "stall"),
                registry=metrics.global_registry(),
                active_fn=lambda: self.active_builds() > 0).start()
        self.scheduler.start()
        # SLO plane: the canary driver (synthetic builds through each
        # alive worker; off by default — `makisu-tpu fleet` turns it
        # on) and the rule evaluator over the front door's own vitals
        # plus the canary series. Constructed after scheduler.start()
        # so the first tick sees a live worker view.
        self.canary = slo_mod.CanaryDriver(
            self.scheduler, interval=canary_interval,
            slow_seconds=canary_slow_seconds)
        rules = slo_mod.default_fleet_rules()
        if slo_config:
            rules = slo_mod.load_rules(slo_config, rules)
        self.slo = slo_mod.SloEvaluator(
            self._slo_probe, rules, interval=slo_interval,
            webhook=alert_webhook, source="fleet")
        self.canary.start()
        self.slo.start()
        # Continuous profiling: the front door samples its own process
        # too (routing, forwarding, canaries), ownership-gated exactly
        # like the worker — in an in-process fleet the first server
        # armed the sampler and everyone shares it. A firing
        # page-severity fleet alert snapshots it next to the bundles.
        from makisu_tpu.utils import profiler as profiler_mod
        self._diag_out = diag_out
        self._profiler_owner = False
        self.profiler = profiler_mod.process_profiler()
        profile_hz = profiler_mod.resolve_hz()
        if self.profiler is None and profile_hz > 0:
            self.profiler = profiler_mod.SamplingProfiler(
                hz=profile_hz).start()
            profiler_mod.set_process_profiler(self.profiler)
            self._profiler_owner = True
        self.slo.manager.on_fire = self._profile_on_page

    def get_request(self):
        request, _ = super().get_request()
        return request, ("fleet", 0)

    def handle_error(self, request, client_address) -> None:
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
            return  # client hung up; normal churn
        super().handle_error(request, client_address)

    def serve_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def server_close(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        from makisu_tpu.utils import profiler as profiler_mod
        if self._profiler_owner and self.profiler is not None:
            self.profiler.stop()
            if profiler_mod.process_profiler() is self.profiler:
                profiler_mod.set_process_profiler(None)
        self.slo.stop()
        self.canary.stop()
        events.remove_global_sink(self._collector_sink)
        events.remove_global_sink(self._recorder_sink)
        self.scheduler.stop()
        super().server_close()

    def active_builds(self) -> int:
        with self._mu:
            return len(self._pending)

    def trace_events(self) -> list[dict]:
        """Snapshot of the merged-trace collector ring (lock-free,
        retried on concurrent mutation)."""
        return metrics.snapshot_concurrent(self._trace_events)

    def collect_serve_access(self) -> list[dict]:
        """Fetch every alive worker's ``/serve/access`` ledger and
        return the rows as worker-tagged ``serve_access`` events. In
        a REAL fleet the workers are separate processes, so their
        access rows (the bytes-on-wire input to the merged trace)
        never reach this process's sinks on their own — the shutdown
        merge pulls them here. Rows keep the ledger's own timestamps,
        identical to the worker's direct emission, so in-process
        fleets (which see both copies) dedupe in the assembler."""
        from makisu_tpu.worker.client import WorkerClient
        stats = self.scheduler.stats()
        out: list[dict] = []
        for w in stats["workers"]:
            if not w["alive"]:
                continue
            client = WorkerClient(w["socket"], connect_timeout=2.0,
                                  control_timeout=5.0, retries=0)
            try:
                conn, resp = client._control("/serve/access")
                try:
                    if resp.status != 200:
                        continue
                    entries = json.loads(resp.read()).get("entries",
                                                          [])
                finally:
                    conn.close()
            except (OSError, RuntimeError, ValueError):
                continue
            for entry in entries:
                ev = dict(entry)
                ev["type"] = "serve_access"
                ev["worker"] = w["id"]
                out.append(ev)
        return out

    # -- the routing/forwarding path ---------------------------------------

    def route_build(self, argv: list[str], tenant: str, emit,
                    traceparent: str = "") -> int:
        """Admit, route, forward, failover. ``emit(line)`` streams
        NDJSON frames to the submitting client; the terminal frame is
        always emitted exactly once (a synthesized failure frame when
        every attempt is exhausted).

        Every build gets its own trace registry here — ADOPTED from
        the submitter's ``traceparent`` when one arrived (malformed
        values mint fresh ids, counted) — so the front door's
        admit/route/forward spans, the worker's build (which adopts
        the forward span's context), and the peer/serve fetches it
        issues all share ONE trace id. The spans leave the process as
        events (global sinks: the flight recorder, the promoted
        ``--events-out`` writer, the merged-trace collector)."""
        t0 = time.monotonic()
        context_key, command = build_identity(argv)
        registry = metrics.MetricsRegistry()
        metrics.adopt_inbound(registry, traceparent)
        reg_token = metrics.set_build_registry(registry)
        with self._mu:
            self._seq += 1
            seq = self._seq
            self._pending[seq] = {
                "id": seq, "tenant": tenant, "state": "admitting",
                "context": context_key, "command": command,
                "worker": "", "enqueued_mono": t0,
                "trace_id": registry.trace_id,
            }
        scheduler = self.scheduler
        quota_wait = 0.0
        exclude: set[str] = set()
        exit_code = 1
        terminal_sent = False
        events.emit("build_start", trace_id=registry.trace_id,
                    command="fleet_build", role="frontdoor",
                    tenant=tenant or "")
        try:
            with metrics.span("fleet_build", tenant=tenant or "",
                              context=os.path.basename(context_key)
                              if context_key else command or "?"):
                with metrics.span("fleet_admit", tenant=tenant or ""):
                    quota_wait = scheduler.admit(tenant, context_key)
                for attempt in range(self.max_attempts):
                    try:
                        with metrics.span("fleet_route",
                                          attempt=attempt):
                            worker, verdict, reason = scheduler.route(
                                context_key, tenant, exclude=exclude,
                                attempt=attempt)
                    except NoWorkersError as e:
                        emit(json.dumps({"level": "error",
                                         "msg": str(e)}))
                        break
                    with self._mu:
                        row = self._pending.get(seq)
                        if row is not None:
                            row.update(state="forwarded",
                                       worker=worker.spec.id,
                                       verdict=verdict)
                    forward_argv = argv
                    if worker.spec.storage:
                        forward_argv = rewrite_storage(
                            argv, worker.spec.storage)
                    # Prewarm: a context-keyed build routed AWAY from
                    # its session holder (placement change, drain,
                    # health demotion, failover) pushes the session
                    # snapshot's chunk plan at the target over the
                    # peer wire first, so the build lands on a warm
                    # restore instead of a cold rebuild. Best-effort
                    # and bounded; affinity routes skip it — the
                    # session is already there.
                    if context_key and verdict != "affinity":
                        with metrics.span("fleet_prewarm",
                                          worker=worker.spec.id):
                            self._prewarm(context_key, worker)
                    # No-wait admission only when a refusal still has
                    # somewhere ELIGIBLE to go (dead/draining workers
                    # are not alternatives), never for an affinity
                    # route — waiting at the session holder (~1.15s
                    # warm rebuild) beats a cold build elsewhere by
                    # ~50x — and never on the LAST attempt: a fully
                    # saturated fleet must end with the build queueing
                    # somewhere, not with every worker having politely
                    # refused it.
                    no_wait = (verdict != "affinity"
                               and attempt + 1 < self.max_attempts
                               and scheduler.eligible_count(
                                   exclude | {worker.spec.id}) >= 1)
                    # One forward span per attempt: failover attempts
                    # land as SIBLING subtrees under fleet_build, each
                    # carrying its worker/verdict — and the worker
                    # adopts THIS span's context, so its whole build
                    # tree nests under the attempt that ran it.
                    with metrics.span("fleet_forward",
                                      worker=worker.spec.id,
                                      verdict=verdict,
                                      attempt=attempt):
                        outcome, code = self._forward(
                            worker, forward_argv, tenant, emit,
                            no_wait,
                            terminal_extra={
                                "worker": worker.spec.id,
                                "fleet_verdict": verdict,
                                "fleet_reason": reason,
                                "fleet_attempts": attempt + 1,
                                "quota_wait_seconds": round(
                                    quota_wait, 3),
                                "trace_id": registry.trace_id,
                            })
                    if outcome == "done":
                        scheduler.note_build_done(worker.spec.id)
                        exit_code = code
                        terminal_sent = True
                        return code
                    scheduler.note_worker_failure(worker.spec.id,
                                                  outcome)
                    exclude.add(worker.spec.id)
                    log.warning("fleet: build attempt %d on %s failed "
                                "(%s); failing over", attempt + 1,
                                worker.spec.id, outcome)
                return exit_code
        finally:
            if not terminal_sent:
                emit(json.dumps({
                    "build_code": str(exit_code),
                    "exit_code": exit_code,
                    "error": "fleet: no worker could run this build",
                    "elapsed_seconds": round(time.monotonic() - t0, 3),
                    "quota_wait_seconds": round(quota_wait, 3),
                    "tenant": tenant,
                    "trace_id": registry.trace_id,
                }))
            scheduler.release(tenant)
            latency = time.monotonic() - t0
            with self._mu:
                self._pending.pop(seq, None)
                if exit_code == 0:
                    self._done_ok += 1
                else:
                    self._done_failed += 1
                self._latencies.append(latency)
            metrics.global_registry().observe(
                metrics.FLEET_BUILD_LATENCY, latency,
                buckets=_LATENCY_BUCKETS,
                tenant=scheduler.tenant_label(tenant))
            events.emit("build_end", trace_id=registry.trace_id,
                        exit_code=exit_code)
            metrics.reset_build_registry(reg_token)

    def _prewarm(self, context_key: str, worker) -> bool:
        """Best-effort session-snapshot push: pull the context's
        recipe from the best source worker (session holders first),
        POST it at the routed-to target, let the target fetch the
        chunks over the existing peer wire. Every failure is swallowed
        — the build proceeds cold, exactly as before prewarm existed —
        but the attempt lands in the decision ledger either way."""
        from makisu_tpu.worker.client import WorkerClient
        scheduler = self.scheduler
        target_id = worker.spec.id
        recipe = None
        source_id = ""
        for wid, socket_path in scheduler.snapshot_sources(
                context_key, exclude={target_id}):
            client = WorkerClient(socket_path, connect_timeout=2.0,
                                  control_timeout=10.0, retries=0)
            try:
                recipe = client.session_snapshot(context_key)
                source_id = wid
                break
            except (OSError, RuntimeError, ValueError):
                continue
        if recipe is None:
            # Nothing to push: no snapshot exists anywhere (a cold
            # context) — not a failure worth ledger noise.
            return False
        target = WorkerClient(worker.spec.socket_path,
                              connect_timeout=2.0,
                              control_timeout=30.0, retries=0)
        payload: dict = {"recipe": recipe}
        if worker.spec.storage:
            payload["storage"] = worker.spec.storage
        try:
            result = target.restore_session(payload)
            ok = bool(result.get("ok"))
            reason = str(result.get("reason", ""))
        except (OSError, RuntimeError, ValueError) as e:
            ok, reason = False, f"push_failed:{type(e).__name__}"
        scheduler.note_prewarm(context_key, target_id, ok,
                               reason or "staged", source=source_id)
        return ok

    def checkpoint_worker(self, worker_id: str) -> int:
        """POST /sessions/snapshot at one worker (the drain hand-off);
        returns the number of sessions checkpointed (0 on any
        failure)."""
        from makisu_tpu.worker.client import WorkerClient
        with self.scheduler._mu:
            state = self.scheduler.workers.get(worker_id)
            socket_path = state.spec.socket_path if state else ""
        if not socket_path:
            return 0
        client = WorkerClient(socket_path, connect_timeout=2.0,
                              control_timeout=30.0, retries=0)
        try:
            return int(client.snapshot_sessions().get(
                "snapshotted", 0))
        except (OSError, RuntimeError, ValueError) as e:
            log.warning("fleet: drain checkpoint of %s failed: %s",
                        worker_id, e)
            return 0

    def _forward(self, worker, argv: list[str], tenant: str, emit,
                 no_wait: bool, terminal_extra: dict,
                 ) -> tuple[str, int]:
        """One attempt against one worker. Returns ``(outcome, code)``
        where outcome is ``done`` (terminal frame relayed), or the
        failover reason: ``unreachable`` | ``refused`` |
        ``midstream``."""
        import http.client as http_client

        from makisu_tpu.worker.client import _UnixHTTPConnection
        headers = {"Content-Type": "application/json"}
        if tenant:
            headers["X-Makisu-Tenant"] = tenant
        if no_wait:
            headers["X-Makisu-No-Wait"] = "1"
        # The worker adopts the current span's context — the
        # fleet_forward span this attempt runs under — so its whole
        # build tree nests under this attempt in the merged trace.
        # Fleet provenance rides the body into the build's history
        # record (worker, verdict, attempts, quota wait).
        headers["traceparent"] = metrics.current_traceparent()
        body = json.dumps({
            "argv": argv,
            "fleet": {
                # The scheduler-assigned id ("w0"), not the socket
                # path: the worker records this as its history
                # provenance, and every other surface (terminal
                # frames, top, doctor, report --fleet) names workers
                # by id — the history record must cross-reference.
                "worker": terminal_extra.get("worker", ""),
                "verdict": terminal_extra.get("fleet_verdict", ""),
                "attempts": terminal_extra.get("fleet_attempts", 1),
                "quota_wait_seconds": terminal_extra.get(
                    "quota_wait_seconds", 0.0),
            },
        }).encode()
        conn = _UnixHTTPConnection(worker.spec.socket_path,
                                   STREAM_READ_TIMEOUT,
                                   connect_timeout=5.0)
        try:
            try:
                conn.request("POST", "/build", body=body,
                             headers=headers)
                resp = conn.getresponse()
            except (OSError, http_client.HTTPException):
                return "unreachable", 1
            if resp.status == 503:
                resp.read()
                return "refused", 1
            if resp.status != 200:
                # The worker answered but can't run this (bad argv
                # would 400 on every worker): relay as a failure, no
                # failover churn.
                emit(json.dumps({
                    "level": "error",
                    "msg": f"worker {worker.spec.id} rejected build: "
                           f"HTTP {resp.status}"}))
                emit(json.dumps({"build_code": "1", "exit_code": 1,
                                 **terminal_extra}))
                return "done", 1
            from makisu_tpu.worker.client import (
                iter_stream_lines,
                terminal_exit_code,
            )
            try:
                # One framing loop shared with WorkerClient.build
                # (iter_stream_lines) — the forwarder only json-parses
                # candidate TERMINAL lines; everything else passes
                # through verbatim.
                for line in iter_stream_lines(resp):
                    payload = None
                    if b'"build_code"' in line:
                        try:
                            payload = json.loads(line)
                        except ValueError:
                            payload = None
                    if payload is not None \
                            and "build_code" in payload:
                        payload.update(terminal_extra)
                        emit(json.dumps(payload))
                        return "done", terminal_exit_code(payload)
                    # Tee worker build events into the front door's
                    # own sinks (worker-tagged, original timestamps)
                    # — this is what makes the fleet's --events-out /
                    # merged trace CROSS-process: the worker's span
                    # events land beside the forward span that owns
                    # them. The frame still forwards to the client
                    # verbatim.
                    if b'"event"' in line:
                        try:
                            frame = json.loads(line)
                        except ValueError:
                            frame = None
                        if isinstance(frame, dict) \
                                and isinstance(frame.get("event"),
                                               dict):
                            teed = dict(frame["event"])
                            teed.setdefault("worker", worker.spec.id)
                            events.deliver(teed)
                    emit(line.decode(errors="replace"))
                # EOF without a terminal frame: the worker died.
                return "midstream", 1
            except (OSError, http_client.HTTPException):
                # A SIGKILLed worker surfaces as IncompleteRead (an
                # HTTPException, not an OSError) on a chunked stream.
                return "midstream", 1
        finally:
            conn.close()

    # -- introspection -----------------------------------------------------

    def aggregated_metrics(self) -> str:
        """The fleet ``GET /metrics`` payload: the front door's own
        process series plus every ALIVE worker's scrape re-exported
        under a ``worker="wN"`` label, merged into one valid
        exposition (one family group per metric) — a single Prometheus
        target covers the whole fleet. A worker whose scrape fails
        costs its own timeout and a counted error, never the whole
        response. The scrapes fan out in parallel, like /builds."""
        from concurrent.futures import ThreadPoolExecutor

        from makisu_tpu.worker.client import WorkerClient
        stats = self.scheduler.stats()
        alive = [w for w in stats["workers"] if w["alive"]]
        g = metrics.global_registry()

        def scrape(w):
            client = WorkerClient(w["socket"], connect_timeout=2.0,
                                  control_timeout=5.0, retries=0)
            try:
                text = client.metrics()
            except (OSError, RuntimeError, ValueError):
                g.counter_add(metrics.FLEET_AGGREGATED_SCRAPES,
                              result="error")
                return w, None
            g.counter_add(metrics.FLEET_AGGREGATED_SCRAPES,
                          result="ok")
            return w, text

        if alive:
            with ThreadPoolExecutor(min(8, len(alive))) as pool:
                fetched = list(pool.map(scrape, alive))
        else:
            fetched = []
        # makisu_worker_up: 1 iff this scrape round actually reached
        # the worker — dead workers (never scraped) and alive-but-
        # failed scrapes both read 0. Rendered from a throwaway
        # registry so the gauge reflects THIS response, not a stale
        # process-global value for a worker that vanished.
        reachable = {w["id"] for w, text in fetched if text is not None}
        up = metrics.MetricsRegistry()
        for w in stats["workers"]:
            up.gauge_set(metrics.WORKER_UP,
                         1 if w["id"] in reachable else 0,
                         worker=w["id"])
        parts = [metrics.render_prometheus(),
                 metrics.render_prometheus(up)]
        for w, text in fetched:
            if text is not None:
                parts.append(metrics.relabel_prometheus(
                    text, worker=w["id"]))
        return metrics.merge_prometheus(parts)

    def _slo_probe(self) -> dict:
        """The fleet evaluator's sample: front-door build counters,
        canary series, and scheduler-derived level signals. Every
        input already exists — this just snapshots it."""
        with self._mu:
            ok, failed = self._done_ok, self._done_failed
        counters: dict = {
            "builds_started": float(ok + failed),
            "builds_failed": float(failed),
        }
        counters.update(self.canary.counters())
        stats = self.scheduler.stats()
        alive = [w for w in stats["workers"] if w["alive"]]
        version = stats["peer_map_version"]
        acked = stats.get("peer_acked", {})
        levels: dict = {
            # Alive workers that have not acked the current peer map.
            "peer_map_lag": float(sum(
                1 for w in alive if acked.get(w["id"]) != version)),
            "dead_workers": float(
                len(stats["workers"]) - len(alive)),
            "frontdoor_queue": float(stats["frontdoor_waiting"]),
        }
        levels.update(self.canary.levels())
        return {"counters": counters, "levels": levels}

    def alerts(self) -> dict:
        """``GET /alerts``: the front door's own alert snapshot plus
        every alive worker's, fanned out in parallel (same discipline
        as /builds — one slow worker costs its own timeout)."""
        from concurrent.futures import ThreadPoolExecutor

        from makisu_tpu.worker.client import WorkerClient
        out = self.slo.manager.snapshot()
        out["source"] = "fleet"
        out["rules"] = [r.name for r in self.slo.rules]
        out["canary"] = self.canary.status()
        stats = self.scheduler.stats()
        alive = [w for w in stats["workers"] if w["alive"]]

        def fetch(w):
            client = WorkerClient(w["socket"], connect_timeout=2.0,
                                  control_timeout=5.0, retries=0)
            try:
                return w, client.alerts()
            except (OSError, RuntimeError, ValueError):
                return w, None

        if alive:
            with ThreadPoolExecutor(min(8, len(alive))) as pool:
                fetched = list(pool.map(fetch, alive))
        else:
            fetched = []
        workers: dict = {}
        for w, payload in fetched:
            workers[w["id"]] = (payload if payload is not None
                                else {"error": "unreachable"})
        for w in stats["workers"]:
            if not w["alive"]:
                workers[w["id"]] = {"error": "dead"}
        out["workers"] = workers
        return out

    def profile(self, seconds: float) -> dict:
        """``GET /profile?seconds=N``: ask every alive worker for an
        on-demand capture window in parallel (same fan-out discipline
        as /metrics — a dead worker costs its own timeout, never the
        round) and merge the answers into one fleet-wide
        ``makisu-tpu.profile.v1`` document with per-worker vitals."""
        from concurrent.futures import ThreadPoolExecutor

        from makisu_tpu.utils import profiler as profiler_mod
        from makisu_tpu.worker.client import WorkerClient
        seconds = min(max(float(seconds), 0.1), 30.0)
        stats = self.scheduler.stats()
        alive = [w for w in stats["workers"] if w["alive"]]

        def capture(w):
            client = WorkerClient(w["socket"], connect_timeout=2.0,
                                  control_timeout=10.0, retries=0)
            try:
                return w, client.profile(seconds=seconds)
            except (OSError, RuntimeError, ValueError):
                return w, None

        if alive:
            with ThreadPoolExecutor(min(8, len(alive))) as pool:
                fetched = list(pool.map(capture, alive))
        else:
            fetched = []
        docs = {w["id"]: doc for w, doc in fetched if doc is not None}
        merged = profiler_mod.merge_profiles(docs)
        merged["unreachable"] = sorted(
            w["id"] for w, doc in fetched if doc is None)
        return merged

    def profiler_health(self) -> dict:
        if self.profiler is None:
            return {"enabled": False, "hz": 0.0, "samples_total": 0,
                    "dropped": 0, "throttled": 0, "distinct_stacks": 0,
                    "overhead_fraction": 0.0}
        return self.profiler.stats()

    def _profile_on_page(self, payload: dict) -> None:
        """AlertManager ``on_fire`` hook: a page-severity fleet alert
        writes the front door's sampler snapshot beside the bundles."""
        from makisu_tpu.utils import flightrecorder
        from makisu_tpu.utils import profiler as profiler_mod
        sampler = self.profiler
        if sampler is None or not sampler.samples_total:
            return
        rule = str(payload.get("rule", "page")).replace("/", "_")
        profiler_mod.write_artifact(
            flightrecorder.forced_profile_path(
                self._diag_out, f"alert-{rule}"),
            sampler.snapshot(command=f"alert-{rule}"))

    def health(self) -> dict:
        """Worker-shaped ``/healthz`` (so ``top`` and WorkerClient
        work against the fleet socket) plus the ``fleet`` section and
        a ``self`` section — the front door's OWN vitals (ROADMAP item
        1 named it the fleet's observability blind spot): poll ages,
        peer-map version fan-out, decision-ring stats, progress
        clock, forensics armament."""
        from makisu_tpu.utils import flightrecorder
        stats = self.scheduler.stats()
        with self._mu:
            pending = len(self._pending)
            ok, failed = self._done_ok, self._done_failed
            latencies = list(self._latencies)
        alive = [w for w in stats["workers"] if w["alive"]]
        poll_ages = [w["last_poll_age_seconds"]
                     for w in stats["workers"]
                     if w["last_poll_age_seconds"] is not None]
        decisions = stats.get("recent_decisions", [])
        ring_verdicts: dict[str, int] = {}
        for row in decisions:
            v = row.get("verdict", "?")
            ring_verdicts[v] = ring_verdicts.get(v, 0) + 1
        version = stats["peer_map_version"]
        acked = stats.get("peer_acked", {})
        stale_acks = sorted(
            w["id"] for w in alive
            if acked.get(w["id"]) is not None
            and acked[w["id"]] < version)
        g = metrics.global_registry()
        self_section = {
            "poll_interval_seconds": self.scheduler.poll_interval,
            "oldest_poll_age_seconds": (round(max(poll_ages), 3)
                                        if poll_ages else None),
            "peer_map": {
                "version": version,
                "acked": acked,
                "stale_acks": stale_acks,
            },
            "decision_ring": {
                "size": len(decisions),
                "verdicts": ring_verdicts,
            },
            "last_progress_seconds": round(
                flightrecorder.last_progress_seconds(), 3),
            "events_dropped": int(g.counter_total(
                "makisu_events_dropped_total")),
            "watchdog_armed": self._watchdog is not None,
        }
        return {
            "status": "ok" if alive else "degraded",
            "role": "fleet",
            "uptime_seconds": round(
                time.monotonic() - self._started_mono, 3),
            "builds_started": ok + failed + pending,
            "builds_succeeded": ok,
            "builds_failed": failed,
            "active_builds": pending,
            "last_progress_seconds": round(
                flightrecorder.last_progress_seconds(), 3),
            "queue": {
                "depth": stats["frontdoor_waiting"],
                "max_concurrent_builds": 0,
                "wait_seconds": {},
                "latency_seconds": metrics.percentile_stats(latencies),
                "tenant_latency_seconds": {},
            },
            "fleet": stats,
            "alerts": self.slo.manager.digest(),
            "profiler": self.profiler_health(),
            "self": self_section,
        }

    def builds(self) -> dict:
        """Aggregated ``GET /builds``: every alive worker's view, rows
        tagged with the worker id, plus the front door's own pending
        (admitting/forwarded) rows. The per-worker GETs fan out in
        parallel: one slow-but-connectable worker must cost the
        aggregate its OWN timeout, not a serial sum that freezes every
        ``top`` poller."""
        from concurrent.futures import ThreadPoolExecutor

        from makisu_tpu.worker.client import WorkerClient
        stats = self.scheduler.stats()
        alive = [w for w in stats["workers"] if w["alive"]]
        inflight: list[dict] = []
        recent: list[dict] = []

        def fetch(w):
            client = WorkerClient(w["socket"], connect_timeout=2.0,
                                  control_timeout=5.0, retries=0)
            try:
                return w, client.builds()
            except (OSError, RuntimeError, ValueError):
                return w, None

        if alive:
            with ThreadPoolExecutor(min(8, len(alive))) as pool:
                fetched = list(pool.map(fetch, alive))
        else:
            fetched = []
        for w, payload in fetched:
            if payload is None:
                continue
            for row in payload.get("inflight", []):
                row = dict(row)
                row["worker"] = w["id"]
                inflight.append(row)
            for row in payload.get("recent", []):
                row = dict(row)
                row["worker"] = w["id"]
                recent.append(row)
        now = time.monotonic()
        with self._mu:
            pending_rows = [
                {"id": -row["id"], "worker": row["worker"] or "-",
                 "tenant": row["tenant"], "state": row["state"],
                 "command": row["command"],
                 "tag": os.path.basename(row["context"] or ""),
                 "queue_wait_seconds": round(
                     now - row["enqueued_mono"], 3),
                 "age_seconds": round(now - row["enqueued_mono"], 3),
                 "progress_age_seconds": 0.0, "cache": {}}
                for row in self._pending.values()
                if row["state"] == "admitting"]
        # Workers already serve `recent` newest-first; keep their
        # relative order under the merge (no cross-worker clock to
        # sort by).
        return {
            "queue_depth": stats["frontdoor_waiting"],
            "max_concurrent_builds": 0,
            "inflight": pending_rows + inflight,
            "recent": recent[:32],
        }
