"""Fleet scheduler core: worker registry + routing decisions.

The policy, in priority order (every decision lands on the event bus /
decision ledger as ``source=fleet`` and in ``makisu_fleet_route_total``):

1. **affinity** — the worker holding a resident build session for the
   build's context identity (polled from each worker's ``/sessions``,
   seeded by the scheduler's own sticky placement memo before the poll
   catches up). This is the fleet-wide extension of PR 10's O(1)
   warm-rebuild state: landing on the session holder costs ~1.15s,
   landing anywhere else pays the cold path.
2. **spillover** — no session anywhere: consistent-hash placement over
   the alive workers (so future builds of the same context converge on
   one owner even across scheduler restarts), degrading to least-loaded
   when the hash owner is saturated past ``spillover_queue_depth``.
3. **failover** — the chosen worker was unreachable, refused admission
   (the ``X-Makisu-No-Wait`` 503), or died mid-stream: the next-best
   worker is chosen with the failed one excluded.
4. **quota_denied** — the tenant is at its in-flight quota: the build
   waits in the front door's FIFO (:class:`_SlotGate` — the worker
   admission queue's slot-transfer mechanics over front-door slots;
   strict arrival order, no barging) and the wait is recorded.

The scheduler also publishes the peer map (``POST /peers``) to every
live worker, so their chunk CASes consult each other before the
registry (``fleet/peers.py``).
"""

from __future__ import annotations

import collections
import hashlib
import json
import threading
import time

from makisu_tpu.utils import ledger
from makisu_tpu.utils import logging as log
from makisu_tpu.utils import metrics

# Metric names: the shared set in utils/metrics.py (one definition for
# the scheduler, peers, the worker's /chunks endpoint, loadgen's report
# reads, and docs/OBSERVABILITY.md's table).
FLEET_ROUTE_TOTAL = metrics.FLEET_ROUTE_TOTAL
FLEET_WORKERS = metrics.FLEET_WORKERS
FLEET_FRONTDOOR_QUEUE = metrics.FLEET_FRONTDOOR_QUEUE
FLEET_TENANT_INFLIGHT = metrics.FLEET_TENANT_INFLIGHT
FLEET_QUOTA_WAIT = metrics.FLEET_QUOTA_WAIT
FLEET_RETRIES = metrics.FLEET_RETRIES

# Virtual nodes per worker on the consistent-hash ring: enough that a
# 3-worker fleet spreads new contexts near-evenly, cheap enough that
# ring rebuilds are free.
_VIRTUAL_NODES = 64

# Hot-tier occupancy/budget ratio past which spillover/failover skip a
# worker (pressure_demoted). 1.25 = 25% over budget: transiently over
# is the evictor's normal operating point right after a build lands —
# only a worker the evictor visibly cannot keep up with is demoted.
STORAGE_PRESSURE_THRESHOLD = 1.25

# Distinct tenants tracked with their own quota budget; overflow
# tenants share one "other" budget (same cardinality discipline as the
# worker's latency rings).
_TENANT_BUDGETS_KEEP = 64
_TENANT_OVERFLOW = "other"

# Recent routing decisions kept for GET /fleet.
_DECISIONS_KEEP = 128


class NoWorkersError(RuntimeError):
    """No eligible worker is alive (routing cannot proceed)."""


class _SlotGate:
    """FIFO admission gate over N slots — the worker admission queue's
    mechanics (a released slot transfers to the OLDEST waiter) applied
    to front-door quota/backpressure slots. A semaphore or condition
    wait would let new arrivals barge past already-blocked builds and
    starve them under a steady stream; strict arrival order is the
    fairness the quota exists to provide. (The transfer engine's
    MemoryBudget stays deliberately barging — small parts must pass a
    blocked oversized reservation — which is why it is not reused
    here.)"""

    def __init__(self, limit: int) -> None:
        self.limit = max(int(limit), 1)
        self._mu = threading.Lock()
        self._running = 0
        self._waiters: collections.deque[threading.Event] = \
            collections.deque()

    @property
    def inflight(self) -> int:
        with self._mu:
            return self._running

    def try_acquire(self) -> bool:
        """Take a slot iff one is free AND nobody is queued ahead."""
        with self._mu:
            if self._running < self.limit and not self._waiters:
                self._running += 1
                return True
            return False

    def acquire(self) -> None:
        with self._mu:
            if self._running < self.limit and not self._waiters:
                self._running += 1
                return
            gate = threading.Event()
            self._waiters.append(gate)
        gate.wait()

    def release(self) -> None:
        with self._mu:
            if self._waiters:
                # The slot transfers: _running stays constant.
                self._waiters.popleft().set()
            else:
                self._running = max(self._running - 1, 0)


class WorkerSpec:
    """Static description of one fleet member.

    ``storage`` is an optional per-worker storage override: when set,
    the front door rewrites each forwarded build's ``--storage`` to it
    — how an in-process fleet (loadgen, tests) models N machines that
    each have their own local disk. Real deployments with one worker
    per host leave it unset."""

    def __init__(self, worker_id: str, socket_path: str,
                 storage: str | None = None) -> None:
        self.id = worker_id
        self.socket_path = socket_path
        self.storage = storage

    @classmethod
    def parse(cls, flag: str, index: int) -> "WorkerSpec":
        """``SOCKET[=STORAGE]`` (the ``--worker`` CLI flag form)."""
        socket_path, _, storage = flag.partition("=")
        return cls(f"w{index}", socket_path, storage or None)


class WorkerState:
    """One worker's live view: poll results + local routing state.
    Mutated only under the scheduler lock."""

    def __init__(self, spec: WorkerSpec) -> None:
        self.spec = spec
        self.alive = False
        self.draining = False
        self.last_error = ""
        self.consecutive_failures = 0
        self.last_poll_mono = 0.0
        # From /healthz + /sessions:
        self.queue_depth = 0
        self.active_builds = 0
        self.max_concurrent = 0
        self.sessions: set[str] = set()
        self.session_hits = 0
        # Session-snapshot digest from /sessions (write/restore
        # tallies + last restore failure) — what `doctor --fleet`'s
        # snapshot_restore_failed finding reads from the fleet row.
        self.session_snapshot: dict = {}
        # Distribution-plane digest from /healthz: what the worker can
        # serve (recipes/packs its builds published) and how much it
        # has served — the peer plane's capacity signal per worker.
        self.serve: dict = {}
        # Storage-plane digest from /healthz: per-plane census totals,
        # LRU-seed state, and cached audit/scrub finding counts — the
        # front door's view of how full (and how consistent) each
        # worker's content planes are.
        self.storage: dict = {}
        self.builds_succeeded = 0
        self.builds_failed = 0
        # Canary-derived health score (EWMA in [0, 1], 1.0 = healthy).
        # Written by the canary driver via set_health_score; a worker
        # that has never been canaried keeps the benefit of the doubt.
        self.health_score = 1.0
        # Active-alert digest from the worker's own /healthz
        # ({"active": n, "page": n, "warn": n}) — what `top`'s ALERTS
        # column and doctor's fleet view read without a /alerts fan-out.
        self.alerts: dict = {}
        # Continuous-profiling digest from /healthz ({"enabled", "hz",
        # "samples_total", "dropped", "overhead_fraction", ...}) — lets
        # doctor --fleet flag a sampler past its overhead budget or
        # dropping stacks without a per-worker /profile fan-out.
        self.profiler: dict = {}
        # Local estimate: builds this front door currently has open
        # against the worker (fresher than any poll).
        self.local_inflight = 0
        self.routed_total = 0

    @property
    def eligible(self) -> bool:
        return self.alive and not self.draining

    @property
    def storage_pressure(self) -> float:
        """Hot-tier occupancy over budget from the worker's /healthz
        storage digest (0.0 when unbudgeted/unknown). Routing demotes
        a worker whose disk is far past its budget — its next build
        pays eviction churn and refetch latency."""
        try:
            budget = self.storage.get("budget") or {}
            return float(budget.get("pressure", 0.0) or 0.0)
        except (TypeError, ValueError, AttributeError):
            return 0.0

    def load(self) -> int:
        """Routing load score: what's queued there plus what we have
        in flight ourselves."""
        return self.queue_depth + max(self.active_builds,
                                      self.local_inflight)

    def snapshot(self) -> dict:
        return {
            "id": self.spec.id,
            "socket": self.spec.socket_path,
            "state": ("draining" if self.draining and self.alive
                      else "alive" if self.alive else "dead"),
            "alive": self.alive,
            "draining": self.draining,
            "queue_depth": self.queue_depth,
            "active_builds": self.active_builds,
            "local_inflight": self.local_inflight,
            "max_concurrent_builds": self.max_concurrent,
            "sessions": sorted(self.sessions),
            "session_hits": self.session_hits,
            "session_snapshot": dict(self.session_snapshot),
            "serve": dict(self.serve),
            "storage": dict(self.storage),
            "builds_succeeded": self.builds_succeeded,
            "builds_failed": self.builds_failed,
            "health_score": round(self.health_score, 4),
            "storage_pressure": round(self.storage_pressure, 4),
            "alerts": dict(self.alerts),
            "profiler": dict(self.profiler),
            "routed_total": self.routed_total,
            "consecutive_failures": self.consecutive_failures,
            "last_error": self.last_error,
            "last_poll_age_seconds": (
                round(time.monotonic() - self.last_poll_mono, 3)
                if self.last_poll_mono else None),
        }


def build_identity(argv: list[str]) -> tuple[str, str]:
    """(context key, command) for one submission, resolved through the
    real CLI parser (hand-rolled argv scanning would miss equals forms
    and abbreviations — the same reason the worker's _effective_flags
    does this). The context key is the realpath of a build's context
    directory; non-build commands have no affinity identity and route
    by load alone."""
    import os

    from makisu_tpu import cli
    try:
        args, _ = cli.make_parser().parse_known_args(argv)
    except SystemExit:
        return "", ""
    command = getattr(args, "command", "") or ""
    context = getattr(args, "context", "") if command == "build" else ""
    if context:
        context = os.path.realpath(os.path.abspath(context))
    return context, command


class FleetScheduler:
    """Worker registry + routing core. Thread-safe; the poll thread
    refreshes worker state, handler threads route against it."""

    def __init__(self, specs: list[WorkerSpec],
                 poll_interval: float = 1.0,
                 tenant_quota: int = 0,
                 max_inflight: int = 0,
                 spillover_queue_depth: int = 2,
                 health_page_threshold: float | None = None) -> None:
        if not specs:
            raise ValueError("a fleet needs at least one worker")
        if health_page_threshold is None:
            # Lazy: scheduler is imported by fleet/__init__ before
            # fleet.slo; resolving the default here keeps one source
            # of truth without an import-time cycle.
            from makisu_tpu.fleet import slo as _slo
            health_page_threshold = _slo.HEALTH_PAGE_THRESHOLD
        # Workers whose canary health score sits at/below this are
        # demoted: spillover/failover prefer healthier peers, and
        # every skip lands in the decision ledger as health_demoted.
        # Affinity still wins — a resident session is worth more than
        # a flaky canary, and demotion must not shed the warm state
        # that makes the worker worth routing to once it recovers.
        self.health_page_threshold = float(health_page_threshold)
        # Disk-pressure demotion threshold: hot-tier bytes over budget
        # past which a worker is skipped by spillover/failover (its
        # next build pays eviction churn while a sibling has headroom).
        # 1.0 is "exactly at budget" — demote only meaningfully past
        # it; affinity still wins for the same reason as health.
        self.storage_pressure_threshold = STORAGE_PRESSURE_THRESHOLD
        self._mu = threading.Lock()
        self.workers: dict[str, WorkerState] = {
            spec.id: WorkerState(spec) for spec in specs}
        self.poll_interval = poll_interval
        self.tenant_quota = max(int(tenant_quota), 0)
        self.spillover_queue_depth = max(int(spillover_queue_depth), 1)
        # Sticky placement memo: context -> worker id the last build
        # was routed to. Seeds affinity before /sessions reflects a
        # freshly-minted session, and keeps convergence across the
        # session TTL.
        self._placements: dict[str, str] = {}
        self._decisions: collections.deque[dict] = collections.deque(
            maxlen=_DECISIONS_KEEP)
        self._ring = self._build_ring([s.id for s in specs])
        # Front-door admission: a global in-flight cap (0 = unlimited)
        # and per-tenant quotas, both strict-FIFO slot gates (arrival
        # order — see _SlotGate).
        self._inflight_budget = (_SlotGate(max_inflight)
                                 if max_inflight > 0 else None)
        self._tenant_budgets: dict[str, _SlotGate] = {}
        self._tenant_labels: set[str] = set()
        self._frontdoor_waiting = 0
        self._peer_version = 0
        self._peer_posted: dict[str, int] = {}
        self._poll_halt = threading.Event()
        self._poll_thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetScheduler":
        """Poll every worker once synchronously (so routing has a
        live view immediately), then keep polling in the background."""
        self.poll_once()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, daemon=True, name="fleet-poll")
        self._poll_thread.start()
        return self

    def stop(self) -> None:
        self._poll_halt.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5)
            self._poll_thread = None

    def _poll_loop(self) -> None:
        while not self._poll_halt.wait(self.poll_interval):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 - the poll must survive
                log.error("fleet poll failed: %s", e)

    # -- polling -----------------------------------------------------------

    def poll_once(self) -> None:
        """Refresh every worker's health + session set, then publish
        the peer map to any worker that hasn't seen the current
        version."""
        from makisu_tpu.worker.client import WorkerClient
        for state in list(self.workers.values()):
            client = WorkerClient(state.spec.socket_path,
                                  connect_timeout=2.0,
                                  control_timeout=5.0, retries=0)
            try:
                health = client.healthz()
                sessions = client.sessions()
            except (OSError, RuntimeError, ValueError) as e:
                self._note_poll_failure(state, str(e))
                continue
            with self._mu:
                was_alive = state.alive
                state.alive = True
                state.consecutive_failures = 0
                state.last_error = ""
                state.last_poll_mono = time.monotonic()
                state.queue_depth = health.queue_depth
                state.active_builds = health.active_builds
                state.max_concurrent = health.max_concurrent_builds
                state.builds_succeeded = health.builds_succeeded
                state.builds_failed = health.builds_failed
                state.sessions = {
                    row.get("context", "")
                    for row in sessions.get("sessions", [])}
                state.session_hits = int(sessions.get("hits", 0))
                state.session_snapshot = dict(
                    sessions.get("snapshot") or {})
                state.serve = dict(health.get("serve") or {})
                state.storage = dict(health.get("storage") or {})
                state.alerts = dict(health.get("alerts") or {})
                state.profiler = dict(health.get("profiler") or {})
                if not was_alive:
                    self._peer_version += 1  # membership changed
                else:
                    # A worker that restarted BETWEEN polls (never
                    # observed dead) comes back holding no peer map —
                    # its /healthz reports a version behind what we
                    # believe it acked. Forget the ack so the normal
                    # publish path re-sends.
                    held = health.get("peer_map_version")
                    posted = self._peer_posted.get(state.spec.id)
                    if held is not None and posted is not None \
                            and int(held) < posted:
                        del self._peer_posted[state.spec.id]
        self._publish_worker_gauges()
        self._publish_peer_map()

    def _note_poll_failure(self, state: WorkerState, error: str) -> None:
        with self._mu:
            state.consecutive_failures += 1
            state.last_error = error
            state.last_poll_mono = time.monotonic()
            if state.alive:
                state.alive = False
                state.sessions = set()
                self._peer_version += 1
                log.warning("fleet: worker %s unreachable: %s",
                            state.spec.id, error)

    def _publish_worker_gauges(self) -> None:
        with self._mu:
            counts = {"alive": 0, "dead": 0, "draining": 0}
            for state in self.workers.values():
                if state.draining and state.alive:
                    counts["draining"] += 1
                elif state.alive:
                    counts["alive"] += 1
                else:
                    counts["dead"] += 1
        g = metrics.global_registry()
        for key, n in counts.items():
            g.gauge_set(FLEET_WORKERS, n, state=key)

    def _publish_peer_map(self) -> None:
        """POST the current peer map to every live worker that hasn't
        acknowledged this version. Draining workers stay in the map —
        they are alive and their chunks are exactly what a drained
        context's next host wants to fetch."""
        with self._mu:
            version = self._peer_version
            sockets = [s.spec.socket_path
                       for s in self.workers.values() if s.alive]
            targets = [s for s in self.workers.values()
                       if s.alive
                       and self._peer_posted.get(s.spec.id) != version]
        if not targets:
            return
        from makisu_tpu.worker.client import _UnixHTTPConnection
        body = json.dumps({"version": version,
                           "peers": sockets}).encode()
        for state in targets:
            conn = _UnixHTTPConnection(state.spec.socket_path, 5.0,
                                       connect_timeout=2.0)
            try:
                conn.request("POST", "/peers", body=body, headers={
                    "Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = json.loads(resp.read() or b"{}")
                if resp.status != 200:
                    continue
                if payload.get("applied"):
                    with self._mu:
                        self._peer_posted[state.spec.id] = version
                else:
                    # The worker holds a HIGHER version (a previous
                    # front door published it before we restarted and
                    # our counter started over). Adopt it: jump past
                    # the worker's version so the next publish wins
                    # everywhere — otherwise this worker would keep a
                    # stale peer map forever while we believed it
                    # up to date.
                    worker_version = int(payload.get("version", 0))
                    with self._mu:
                        self._peer_version = max(self._peer_version,
                                                 worker_version + 1)
                    log.info("fleet: worker %s holds peer map v%d > "
                             "our v%d; republishing as v%d",
                             state.spec.id, worker_version, version,
                             self._peer_version)
            except (OSError, ValueError) as e:
                log.debug("peer map post to %s failed: %s",
                          state.spec.id, e)
            finally:
                conn.close()

    # -- routing -----------------------------------------------------------

    @staticmethod
    def _build_ring(worker_ids: list[str]) -> list[tuple[int, str]]:
        ring = []
        for wid in worker_ids:
            for v in range(_VIRTUAL_NODES):
                h = hashlib.sha256(f"{wid}#{v}".encode()).digest()
                ring.append((int.from_bytes(h[:8], "big"), wid))
        ring.sort()
        return ring

    def _ring_owner(self, key: str,
                    eligible: set[str]) -> str | None:
        """First eligible worker clockwise of the key's ring point —
        stable under membership churn (only keys owned by a
        dead/drained worker move)."""
        if not self._ring or not eligible:
            return None
        point = int.from_bytes(
            hashlib.sha256(key.encode()).digest()[:8], "big")
        import bisect
        start = bisect.bisect_left(self._ring, (point, ""))
        for i in range(len(self._ring)):
            _, wid = self._ring[(start + i) % len(self._ring)]
            if wid in eligible:
                return wid
        return None

    def route(self, context_key: str, tenant: str = "",
              exclude: frozenset[str] | set[str] = frozenset(),
              attempt: int = 0) -> tuple[WorkerState, str, str]:
        """Pick the worker for one build. Returns ``(worker, verdict,
        reason)`` with verdict ``affinity`` | ``spillover`` |
        ``failover`` and the decision recorded. Raises
        :class:`NoWorkersError` when nothing is eligible."""
        with self._mu:
            candidates = {wid: w for wid, w in self.workers.items()
                          if w.eligible and wid not in exclude}
            if not candidates:
                raise NoWorkersError(
                    "no eligible fleet worker (all dead, draining, "
                    "or excluded after failover)")
            chosen = None
            verdict = "spillover"
            reason = ""
            if context_key:
                # 1. Session affinity: a worker that reports a
                # resident session for this context, else the sticky
                # placement memo (a session just minted there hasn't
                # hit a poll yet).
                holders = [w for w in candidates.values()
                           if context_key in w.sessions]
                if holders:
                    chosen = min(holders, key=lambda w: w.load())
                    verdict, reason = "affinity", "session"
                else:
                    memo = self._placements.get(context_key)
                    if memo in candidates:
                        chosen = candidates[memo]
                        verdict, reason = "affinity", "sticky"
            demoted: list[tuple[str, float]] = []
            pressure_demoted: list[tuple[str, float]] = []
            pool = candidates
            if chosen is None:
                # Health demotion (spillover/failover only — a worker
                # holding this context's session was already chosen
                # above regardless of score): drop workers whose
                # canary health score is at/below the page threshold,
                # unless that would empty the pool — a degraded worker
                # beats NoWorkersError.
                healthy = {
                    wid: w for wid, w in candidates.items()
                    if w.health_score > self.health_page_threshold}
                if healthy and len(healthy) < len(candidates):
                    demoted = sorted(
                        (wid, w.health_score)
                        for wid, w in candidates.items()
                        if wid not in healthy)
                    pool = healthy
                # Disk-pressure demotion, same never-strand shape:
                # skip workers far over their storage budget while
                # any peer with headroom remains.
                unpressured = {
                    wid: w for wid, w in pool.items()
                    if w.storage_pressure
                    < self.storage_pressure_threshold}
                if unpressured and len(unpressured) < len(pool):
                    pressure_demoted = sorted(
                        (wid, w.storage_pressure)
                        for wid, w in pool.items()
                        if wid not in unpressured)
                    pool = unpressured
            if chosen is None and context_key:
                # 2. Consistent-hash placement for new contexts.
                owner_id = self._ring_owner(context_key,
                                            set(pool))
                owner = pool.get(owner_id)
                if owner is not None and owner.load() \
                        < self.spillover_queue_depth:
                    chosen, reason = owner, "placed"
                else:
                    reason = "overloaded"
            if chosen is None:
                # 3. Least-loaded (no context identity, or the hash
                # owner is saturated).
                chosen = min(pool.values(),
                             key=lambda w: (w.load(), w.spec.id))
                reason = reason or "no_context"
            if attempt > 0:
                verdict = "failover"
            chosen.local_inflight += 1
            chosen.routed_total += 1
            if context_key:
                self._placements[context_key] = chosen.spec.id
        # Every worker skipped for health gets its own ledgered
        # decision — the routing shift away from a degraded worker is
        # auditable from the same surface as every other verdict.
        for wid, score in demoted:
            self._record_decision(
                context_key or "<no-context>", "health_demoted",
                reason="canary_health", tenant=tenant, worker=wid,
                score=round(score, 4),
                threshold=self.health_page_threshold)
        for wid, pressure in pressure_demoted:
            self._record_decision(
                context_key or "<no-context>", "pressure_demoted",
                reason="storage_pressure", tenant=tenant, worker=wid,
                pressure=round(pressure, 4),
                threshold=self.storage_pressure_threshold)
        self._record_decision(context_key or "<no-context>", verdict,
                              reason=reason, tenant=tenant,
                              worker=chosen.spec.id, attempt=attempt)
        return chosen, verdict, reason

    def eligible_count(self,
                       exclude: frozenset[str] | set[str] = frozenset(),
                       ) -> int:
        """How many workers could take a build right now (alive, not
        draining, not excluded) — what the front door's no-wait
        decision must count: dead or drained workers are not
        'somewhere else to go'."""
        with self._mu:
            return sum(1 for wid, w in self.workers.items()
                       if w.eligible and wid not in exclude)

    def set_health_score(self, worker_id: str, score: float) -> None:
        """Record a worker's canary-derived health score (the canary
        driver calls this after every sweep). Clamped to [0, 1]."""
        with self._mu:
            state = self.workers.get(worker_id)
            if state is not None:
                state.health_score = min(max(float(score), 0.0), 1.0)
        metrics.global_registry().gauge_set(
            metrics.WORKER_HEALTH_SCORE, score, worker=worker_id)

    def canary_targets(self) -> list[tuple[str, str, str]]:
        """``(worker_id, socket_path, storage)`` for every worker a
        canary sweep should probe. Dead workers are skipped (the poll
        already tells the story); DRAINING workers are probed — they
        still serve peer fetches and their health matters for when
        they come back."""
        with self._mu:
            return [(w.spec.id, w.spec.socket_path,
                     w.spec.storage or "")
                    for w in sorted(self.workers.values(),
                                    key=lambda w: w.spec.id)
                    if w.alive]

    def health_scores(self) -> dict[str, float]:
        """Current health score per worker — the fleet SLO probe's
        ``canary_health_score`` level signal."""
        with self._mu:
            return {wid: w.health_score
                    for wid, w in self.workers.items()}

    def snapshot_sources(self, context_key: str,
                         exclude: frozenset[str] | set[str] =
                         frozenset()) -> list[tuple[str, str]]:
        """``(worker_id, socket)`` candidates that may hold a session
        snapshot for ``context_key``, best-first: workers reporting a
        resident session, then the sticky placement memo's worker,
        then every other ALIVE worker (draining included — a draining
        worker's snapshot is exactly what its contexts' next host
        wants to pull). The prewarm path walks this list."""
        with self._mu:
            rows = [(w, context_key in w.sessions,
                     self._placements.get(context_key) == wid)
                    for wid, w in self.workers.items()
                    if w.alive and wid not in exclude]
        rows.sort(key=lambda r: (not r[1], not r[2], r[0].spec.id))
        return [(w.spec.id, w.spec.socket_path) for w, _, _ in rows]

    def note_prewarm(self, context_key: str, worker_id: str,
                     ok: bool, reason: str, source: str = "") -> None:
        """Ledger one prewarm attempt (verdict ``prewarm`` /
        ``prewarm_failed``) so routing-shift warmth is auditable from
        the same decision surface as every route verdict."""
        # Field name is from_worker, not source: the decision row is
        # re-recorded on the cache ledger whose own first argument is
        # the ledger source ("fleet").
        self._record_decision(
            context_key or "<no-context>",
            "prewarm" if ok else "prewarm_failed",
            reason=reason, tenant="", worker=worker_id,
            from_worker=source)

    def note_build_done(self, worker_id: str) -> None:
        """A forwarded build finished (success or failure — outcome
        counts come from the worker's own /healthz poll); drop it from
        the local in-flight estimate."""
        with self._mu:
            state = self.workers.get(worker_id)
            if state is not None:
                state.local_inflight = max(state.local_inflight - 1, 0)

    def note_worker_failure(self, worker_id: str, reason: str) -> None:
        """A forward attempt failed (unreachable / mid-stream death):
        mark the worker dead immediately — the next poll revives it if
        it was a blip — and count the retry."""
        metrics.global_registry().counter_add(FLEET_RETRIES,
                                              reason=reason)
        with self._mu:
            state = self.workers.get(worker_id)
            if state is None:
                return
            state.local_inflight = max(state.local_inflight - 1, 0)
            if reason == "refused":
                # Admission refusal is load, not death.
                return
            if state.alive:
                state.alive = False
                state.sessions = set()
                state.last_error = reason
                self._peer_version += 1
                log.warning("fleet: worker %s failed mid-build (%s); "
                            "marked dead pending next poll",
                            worker_id, reason)

    # -- tenant quotas / front-door admission ------------------------------

    def tenant_label(self, tenant: str) -> str:
        """Bounded metric label for a CLIENT-supplied tenant string:
        past the cap, new tenants aggregate under "other" in every
        fleet series (the same cardinality discipline the worker's
        latency rings apply) — quota budgets use the same key, so the
        label always names the budget that actually gated the build."""
        key = tenant or "default"
        with self._mu:
            if key in self._tenant_labels \
                    or len(self._tenant_labels) < _TENANT_BUDGETS_KEEP:
                self._tenant_labels.add(key)
                return key
        return _TENANT_OVERFLOW

    def _tenant_budget(self, tenant: str) -> "_SlotGate | None":
        if self.tenant_quota <= 0:
            return None
        key = self.tenant_label(tenant)
        with self._mu:
            budget = self._tenant_budgets.get(key)
            if budget is None:
                budget = _SlotGate(self.tenant_quota)
                self._tenant_budgets[key] = budget
            return budget

    def admit(self, tenant: str, context_key: str = "") -> float:
        """Front-door admission: block until the tenant is under its
        in-flight quota (and the global cap, when set) — strict FIFO
        per gate. Returns the seconds waited; a nonzero wait is
        recorded as a ``quota_denied`` decision."""
        t0 = time.monotonic()
        for gate, kind in ((self._tenant_budget(tenant),
                            "tenant_quota"),
                           (self._inflight_budget, "fleet_inflight")):
            if gate is None:
                continue
            if not gate.try_acquire():
                self._note_waiting(+1)
                self._record_decision(
                    context_key or "<no-context>", "quota_denied",
                    reason=kind, tenant=tenant, worker="")
                try:
                    gate.acquire()
                finally:
                    self._note_waiting(-1)
        waited = time.monotonic() - t0
        if waited > 0.000_5:
            metrics.global_registry().observe(
                FLEET_QUOTA_WAIT, waited,
                tenant=self.tenant_label(tenant))
        self._publish_admission_gauges(tenant)
        return waited

    def release(self, tenant: str) -> None:
        budget = self._tenant_budget(tenant)
        if budget is not None:
            budget.release()
        if self._inflight_budget is not None:
            self._inflight_budget.release()
        self._publish_admission_gauges(tenant)

    def _publish_admission_gauges(self, tenant: str) -> None:
        budget = self._tenant_budget(tenant)
        g = metrics.global_registry()
        if budget is not None:
            g.gauge_set(FLEET_TENANT_INFLIGHT, budget.inflight,
                        tenant=self.tenant_label(tenant))
        if self._inflight_budget is not None:
            g.gauge_set(metrics.FLEET_INFLIGHT_BUILDS,
                        self._inflight_budget.inflight)

    def _note_waiting(self, delta: int) -> None:
        with self._mu:
            self._frontdoor_waiting = max(
                self._frontdoor_waiting + delta, 0)
            depth = self._frontdoor_waiting
        metrics.global_registry().gauge_set(FLEET_FRONTDOOR_QUEUE,
                                            depth)

    def frontdoor_waiting(self) -> int:
        with self._mu:
            return self._frontdoor_waiting

    # -- drain -------------------------------------------------------------

    def drain(self, worker_id: str, draining: bool = True) -> bool:
        """Graceful drain: new builds stop routing to the worker, but
        it stays alive — serving peer chunk fetches, finishing its
        in-flight builds — until the operator stops it."""
        with self._mu:
            state = self.workers.get(worker_id)
            if state is None:
                return False
            state.draining = draining
            # Sticky placements toward a draining worker must not pin
            # affinity there (route() re-places on next build).
            if draining:
                self._placements = {
                    ctx: wid for ctx, wid in self._placements.items()
                    if wid != worker_id}
        self._publish_worker_gauges()
        log.info("fleet: worker %s %s", worker_id,
                 "draining" if draining else "undrained")
        return True

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        g = metrics.global_registry()
        with self._mu:
            workers = [w.snapshot()
                       for w in sorted(self.workers.values(),
                                       key=lambda w: w.spec.id)]
            decisions = list(self._decisions)
            tenants = {
                tenant: {"inflight": budget.inflight,
                         "quota": self.tenant_quota}
                for tenant, budget in sorted(
                    self._tenant_budgets.items())}
            placements = dict(self._placements)
            waiting = self._frontdoor_waiting
            peer_version = self._peer_version
            peer_acked = dict(self._peer_posted)
        return {
            "workers": workers,
            "tenant_quota": self.tenant_quota,
            "tenants": tenants,
            "placements": placements,
            "frontdoor_waiting": waiting,
            "peer_map_version": peer_version,
            # Which peer-map version each worker last acknowledged —
            # the fan-out the /healthz self section and `doctor
            # --fleet` read to spot a worker stuck on a stale map.
            "peer_acked": peer_acked,
            "route_totals": {
                verdict: int(n) for verdict, n in sorted(
                    g.counter_by_label(FLEET_ROUTE_TOTAL,
                                       "verdict").items())},
            "recent_decisions": decisions,
        }

    # -- decision recording ------------------------------------------------

    def _record_decision(self, key: str, verdict: str, reason: str,
                         tenant: str, worker: str,
                         **fields) -> None:
        metrics.global_registry().counter_add(FLEET_ROUTE_TOTAL,
                                              verdict=verdict)
        row = {"ts": round(time.time(), 3), "key": key,
               "verdict": verdict, "reason": reason,
               "tenant": tenant, "worker": worker}
        row.update(fields)
        with self._mu:
            self._decisions.append(row)
        record = dict(fields)
        if worker:
            record["worker"] = worker
        if tenant:
            record["tenant"] = tenant
        # Handler/poll threads carry no bound context; the decision
        # reaches --events-out/--explain-out because `makisu-tpu
        # fleet` promotes the invocation's sinks process-wide
        # (events.promote_context_sinks in cmd_fleet).
        ledger.record("fleet", key, verdict, reason, **record)
