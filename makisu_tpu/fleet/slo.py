"""Fleet SLO plane: declarative rules, burn rates, and canary builds.

Three pieces, layered so each is testable alone:

- **Pure burn-rate math** (:func:`window_delta`, :func:`burn_rate`,
  :func:`multi_window_breach`): multi-window rate evaluation over
  timestamped snapshots of the counters the repo already keeps — no
  new sampling plane. The SRE-style shape: an alert fires only when
  BOTH a fast window (default 5m — is it burning *now*?) and a slow
  window (default 1h — has it burned *enough to matter*?) are at or
  above threshold. Exact-threshold FIRES (``>=``): a rule that says
  0.5 means 0.5 is out of budget.

- **Declarative rules** (:class:`SloRule`): two kinds. ``burn_rate``
  rules name a numerator/denominator counter pair (error ratio,
  canary failure share); ``level`` rules threshold an instantaneous
  signal (p99 latency from the quantile rings, progress age, storage
  bytes, device-probe verdict) with ``breach_for`` consecutive-tick
  fire hysteresis. Built-in defaults per tier
  (:func:`default_worker_rules` / :func:`default_fleet_rules`);
  ``--slo-config`` JSON overrides or extends by rule name.

- **The evaluator and canary driver**: :class:`SloEvaluator` runs a
  background thread that samples a caller-supplied ``probe()`` (the
  worker and front door each expose their existing vitals — rings,
  health counters, scheduler stats) into bounded timestamped rings
  and feeds every rule's verdict to an
  :class:`~makisu_tpu.utils.alerts.AlertManager`.
  :class:`CanaryDriver` (front door only) periodically builds one
  tiny generated context — loadgen's template generator, reused —
  directly on each alive worker, end-to-end through admission,
  cache, and digest verification, scoring each worker's health as an
  EWMA of canary outcomes. The score feeds the scheduler's
  health-demoted routing and the ``worker_health`` rule.
"""

from __future__ import annotations

import collections
import http.client
import os
import shutil
import tempfile
import threading
import time
from typing import Any, Callable, Iterable

from makisu_tpu.utils import alerts as alerts_mod
from makisu_tpu.utils import logging as log
from makisu_tpu.utils import metrics

# Default multi-window pair (seconds): fast catches an active burn,
# slow keeps a blip from paging. Rules may override per-rule; the CI
# smoke scenario shrinks both so alerts fire in test time.
FAST_WINDOW = 300.0
SLOW_WINDOW = 3600.0

# Evaluator counter-ring bound: at the default 5s interval this holds
# well past the slow window; a runaway interval cannot grow it.
_RING_KEEP = 2048

# Health score: EWMA weight of the newest canary outcome, and the
# score at/below which the scheduler demotes a worker (the "page
# threshold" — two consecutive canary failures from a healthy 1.0
# land at 0.36, one success recovers to above it).
HEALTH_ALPHA = 0.4
HEALTH_PAGE_THRESHOLD = 0.5

_VALID_KINDS = ("burn_rate", "level")
_VALID_OPS = ("ge", "le")
_VALID_SEVERITIES = tuple(alerts_mod.SEVERITY_RANK)


# -- pure burn-rate math ----------------------------------------------------


def window_delta(samples: Iterable[tuple[float, float]],
                 window_seconds: float,
                 now: float | None = None) -> float | None:
    """Delta of a cumulative counter over the trailing window.

    ``samples`` are ``(monotonic_ts, value)`` pairs in ascending time
    order. Returns ``None`` when the ring cannot support a rate at
    all — empty, or a single sample (one point has no delta). With at
    least two samples the delta is always defined: the baseline is
    the newest sample at or before the window start, falling back to
    the oldest sample when the ring doesn't yet span the window (a
    partial window reads as "since the beginning" — the behavior that
    lets a fresh process alert before an hour of history exists).
    Counter resets (worker restart) clamp to 0 instead of reporting a
    negative burn."""
    pts = list(samples)
    if len(pts) < 2:
        return None
    if now is None:
        now = pts[-1][0]
    start = now - window_seconds
    baseline = pts[0]
    for ts, value in pts:
        if ts <= start:
            baseline = (ts, value)
        else:
            break
    return max(pts[-1][1] - baseline[1], 0.0)


def burn_rate(num_samples: Iterable[tuple[float, float]],
              den_samples: Iterable[tuple[float, float]],
              window_seconds: float,
              now: float | None = None) -> float | None:
    """Numerator delta ÷ denominator delta over one window. ``None``
    when either ring can't support the window or the denominator saw
    no activity (0/0 is "no traffic", not "all bad")."""
    num = window_delta(num_samples, window_seconds, now)
    den = window_delta(den_samples, window_seconds, now)
    if num is None or den is None or den <= 0:
        return None
    return num / den


def multi_window_breach(num_samples: Iterable[tuple[float, float]],
                        den_samples: Iterable[tuple[float, float]],
                        fast_window: float, slow_window: float,
                        threshold: float,
                        now: float | None = None
                        ) -> tuple[bool, float | None, float | None]:
    """``(breached, fast_rate, slow_rate)``: breached only when BOTH
    windows burn at or above threshold (``>=`` — exact threshold
    fires). Either window undefined → not breached (no data is never
    an outage)."""
    num = list(num_samples)
    den = list(den_samples)
    fast = burn_rate(num, den, fast_window, now)
    slow = burn_rate(num, den, slow_window, now)
    breached = (fast is not None and slow is not None
                and fast >= threshold and slow >= threshold)
    return breached, fast, slow


# -- rules ------------------------------------------------------------------


class SloRule:
    """One declarative rule. Plain data + validation; evaluation lives
    in :class:`SloEvaluator` so rules stay serializable."""

    def __init__(self, name: str, kind: str, severity: str = "warn",
                 threshold: float = 1.0,
                 numerator: str = "", denominator: str = "",
                 fast_window: float = FAST_WINDOW,
                 slow_window: float = SLOW_WINDOW,
                 signal: str = "", op: str = "ge",
                 breach_for: int = 1,
                 message: str = "") -> None:
        if kind not in _VALID_KINDS:
            raise ValueError(f"rule {name!r}: kind must be one of "
                             f"{_VALID_KINDS}, got {kind!r}")
        if severity not in _VALID_SEVERITIES:
            raise ValueError(f"rule {name!r}: severity must be one of "
                             f"{_VALID_SEVERITIES}, got {severity!r}")
        if op not in _VALID_OPS:
            raise ValueError(f"rule {name!r}: op must be one of "
                             f"{_VALID_OPS}, got {op!r}")
        if kind == "burn_rate" and not (numerator and denominator):
            raise ValueError(f"rule {name!r}: burn_rate rules need "
                             "numerator and denominator counter names")
        if kind == "level" and not signal:
            raise ValueError(f"rule {name!r}: level rules need a "
                             "signal name")
        self.name = name
        self.kind = kind
        self.severity = severity
        self.threshold = float(threshold)
        self.numerator = numerator
        self.denominator = denominator
        self.fast_window = float(fast_window)
        self.slow_window = float(slow_window)
        self.signal = signal
        self.op = op
        self.breach_for = max(1, int(breach_for))
        self.message = message

    @classmethod
    def from_dict(cls, raw: dict) -> "SloRule":
        if not isinstance(raw, dict) or not raw.get("name"):
            raise ValueError(f"rule entry must be an object with a "
                             f"name, got {raw!r}")
        return cls(
            name=str(raw["name"]),
            kind=str(raw.get("kind", "level")),
            severity=str(raw.get("severity", "warn")),
            threshold=float(raw.get("threshold", 1.0)),
            numerator=str(raw.get("numerator", "")),
            denominator=str(raw.get("denominator", "")),
            fast_window=float(raw.get("fast_window_seconds",
                                      raw.get("fast_window",
                                              FAST_WINDOW))),
            slow_window=float(raw.get("slow_window_seconds",
                                      raw.get("slow_window",
                                              SLOW_WINDOW))),
            signal=str(raw.get("signal", "")),
            op=str(raw.get("op", "ge")),
            breach_for=int(raw.get("breach_for", 1)),
            message=str(raw.get("message", "")),
        )

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "name": self.name, "kind": self.kind,
            "severity": self.severity, "threshold": self.threshold,
        }
        if self.kind == "burn_rate":
            out.update(numerator=self.numerator,
                       denominator=self.denominator,
                       fast_window_seconds=self.fast_window,
                       slow_window_seconds=self.slow_window)
        else:
            out.update(signal=self.signal, op=self.op,
                       breach_for=self.breach_for)
        if self.message:
            out["message"] = self.message
        return out


def default_worker_rules() -> list[SloRule]:
    """Built-in worker-tier rules over /healthz-grade signals: every
    signal already exists (quantile rings, health counters, census
    digest, device probe, progress clock) — the probe just snapshots
    them."""
    return [
        SloRule("build_error_burn", "burn_rate", severity="page",
                threshold=0.5, numerator="builds_failed",
                denominator="builds_started",
                message="build error ratio burning"),
        SloRule("build_latency_p99", "level", severity="warn",
                threshold=120.0, signal="build_latency_p99",
                breach_for=2,
                message="p99 build latency above target"),
        SloRule("tenant_latency_p99", "level", severity="warn",
                threshold=300.0, signal="tenant_latency_p99",
                breach_for=2,
                message="per-tenant p99 latency above target"),
        SloRule("queue_wait_share", "level", severity="warn",
                threshold=0.5, signal="queue_wait_share",
                breach_for=3,
                message="queue wait dominating build latency"),
        SloRule("progress_stall", "level", severity="page",
                threshold=120.0, signal="progress_age", breach_for=2,
                message="active builds with no observable progress"),
        SloRule("device_probe", "level", severity="page",
                threshold=1.0, signal="device_probe_bad",
                message="device probe wedged or failed"),
        SloRule("storage_budget", "level", severity="warn",
                threshold=float(48 * 1024 ** 3),
                signal="storage_total_bytes",
                message="storage planes above byte budget"),
    ]


def default_fleet_rules() -> list[SloRule]:
    """Built-in front-door rules over scheduler stats + canary series."""
    return [
        SloRule("build_latency_burn", "burn_rate", severity="page",
                threshold=0.5, numerator="canary_bad",
                denominator="canary_total",
                message="canary builds slow or failing"),
        SloRule("fleet_error_burn", "burn_rate", severity="page",
                threshold=0.5, numerator="builds_failed",
                denominator="builds_started",
                message="fleet build error ratio burning"),
        SloRule("worker_health", "level", severity="page",
                threshold=HEALTH_PAGE_THRESHOLD,
                signal="canary_health_score", op="le",
                message="worker health score at/below page threshold"),
        SloRule("canary_digest", "level", severity="page",
                threshold=1.0, signal="canary_digest_mismatch",
                message="canary digests diverged across workers"),
        SloRule("peer_map_stale", "level", severity="warn",
                threshold=1.0, signal="peer_map_lag", breach_for=3,
                message="peer map not acked by all alive workers"),
        SloRule("dead_worker", "level", severity="warn",
                threshold=1.0, signal="dead_workers", breach_for=2,
                message="fleet has dead workers"),
        SloRule("frontdoor_queue", "level", severity="warn",
                threshold=8.0, signal="frontdoor_queue", breach_for=3,
                message="front-door quota queue backing up"),
    ]


def load_rules(path: str,
               defaults: list[SloRule] | None = None) -> list[SloRule]:
    """Load ``--slo-config`` JSON and merge over ``defaults`` by rule
    name: an entry with a known name replaces the built-in (or drops
    it with ``"disabled": true``); an unknown name adds a rule. The
    file is either ``{"rules": [...]}`` or a bare list. Malformed
    input raises ``ValueError`` naming the problem — a bad config
    must fail startup loudly, not silently run without alerting."""
    import json
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    entries = raw.get("rules") if isinstance(raw, dict) else raw
    if not isinstance(entries, list):
        raise ValueError(f"{path}: expected a rule list or "
                         f'{{"rules": [...]}}')
    merged = {r.name: r for r in (defaults or [])}
    for entry in entries:
        if not isinstance(entry, dict) or not entry.get("name"):
            raise ValueError(f"{path}: each rule needs a name: "
                             f"{entry!r}")
        name = str(entry["name"])
        if entry.get("disabled"):
            merged.pop(name, None)
            continue
        base = merged.get(name)
        if base is not None:
            full = dict(base.to_dict())
            full.update(entry)
            merged[name] = SloRule.from_dict(full)
        else:
            merged[name] = SloRule.from_dict(entry)
    return list(merged.values())


# -- evaluator --------------------------------------------------------------


def _iter_labeled(value) -> list[tuple[str, float]]:
    """A probe value is a float (one unlabeled series) or a dict of
    label → float (per-tenant, per-worker)."""
    if isinstance(value, dict):
        return [(str(k), float(v)) for k, v in sorted(value.items())]
    return [("", float(value))]


def slo_interval_seconds() -> float:
    try:
        return float(os.environ.get(
            "MAKISU_TPU_SLO_INTERVAL_SECONDS", "5"))
    except ValueError:
        return 5.0


class SloEvaluator:
    """Background rule evaluation over a caller-supplied probe.

    ``probe()`` returns ``{"counters": {...}, "levels": {...}}`` —
    cumulative counters get sampled into timestamped rings for the
    burn-rate rules; levels are thresholded directly. Each value may
    be a float or a label→float dict (per-tenant, per-worker); a
    labeled series evaluates per label and alerts carry the label.

    ``tick`` is callable directly (tests, and the loadgen scenario's
    deterministic stepping); ``start`` runs it on a daemon thread."""

    def __init__(self, probe: Callable[[], dict],
                 rules: list[SloRule],
                 manager: alerts_mod.AlertManager | None = None,
                 interval: float | None = None,
                 webhook: str = "", source: str = "") -> None:
        self.probe = probe
        self.rules = list(rules)
        self.manager = manager or alerts_mod.AlertManager(
            webhook=webhook, source=source)
        self.interval = (slo_interval_seconds()
                         if interval is None else float(interval))
        self._rings: dict[tuple[str, str],
                          collections.deque] = {}
        self._streaks: dict[tuple[str, str], int] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- evaluation -------------------------------------------------------

    def tick(self, now: float | None = None) -> None:
        """One evaluation pass: sample the probe, feed every rule."""
        if now is None:
            now = time.monotonic()
        try:
            sample = self.probe() or {}
        except Exception as exc:  # noqa: BLE001 - never kills the loop
            log.debug("slo probe failed: %s", exc)
            return
        counters = sample.get("counters") or {}
        levels = sample.get("levels") or {}
        for name, value in counters.items():
            for label, v in _iter_labeled(value):
                ring = self._rings.setdefault(
                    (name, label),
                    collections.deque(maxlen=_RING_KEEP))
                ring.append((now, v))
        for rule in self.rules:
            try:
                if rule.kind == "burn_rate":
                    self._eval_burn(rule, now)
                else:
                    self._eval_level(rule, levels)
            except Exception as exc:  # noqa: BLE001 - rule isolation
                log.debug("slo rule %s failed: %s", rule.name, exc)

    def _eval_burn(self, rule: SloRule, now: float) -> None:
        labels = sorted({lbl for (name, lbl) in self._rings
                         if name == rule.numerator})
        for label in labels:
            num = self._rings.get((rule.numerator, label), ())
            den = self._rings.get((rule.denominator, label), ())
            breached, fast, slow = multi_window_breach(
                num, den, rule.fast_window, rule.slow_window,
                rule.threshold, now)
            message = rule.message
            if fast is not None and slow is not None:
                message += (f" [burn fast={fast:.3f} "
                            f"slow={slow:.3f}]")
            self.manager.observe(
                rule.name, breached, severity=rule.severity,
                label=label,
                value=fast if fast is not None else 0.0,
                threshold=rule.threshold, message=message)

    def _eval_level(self, rule: SloRule, levels: dict) -> None:
        raw = levels.get(rule.signal)
        seen: set[str] = set()
        if raw is not None:
            for label, value in _iter_labeled(raw):
                seen.add(label)
                breached_now = (value >= rule.threshold
                                if rule.op == "ge"
                                else value <= rule.threshold)
                key = (rule.name, label)
                streak = self._streaks.get(key, 0) + 1 \
                    if breached_now else 0
                self._streaks[key] = streak
                self.manager.observe(
                    rule.name, streak >= rule.breach_for,
                    severity=rule.severity, label=label,
                    value=value, threshold=rule.threshold,
                    message=rule.message)
        # A label that vanished from the probe (tenant aged out of the
        # ring, worker removed) reads as cleared — a firing alert must
        # not be immortal just because its subject disappeared.
        for key in [k for k in self._streaks
                    if k[0] == rule.name and k[1] not in seen]:
            self._streaks[key] = 0
            self.manager.observe(rule.name, False,
                                 severity=rule.severity,
                                 label=key[1], message=rule.message)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "SloEvaluator":
        if self.interval <= 0 or self._thread is not None:
            return self
        # Process-level evaluation thread: must not pin any build's
        # registry/log context.  # check: allow(ctx-propagation)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="slo-evaluator")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.tick()

    def stop(self) -> None:
        self._stop.set()


# -- synthetic canary builds ------------------------------------------------


def _canary_layer_digests(storage: str, tag: str) -> list[str]:
    """Layer digests of a built canary tag, read from the serving
    worker's storage — the same byte-identity oracle loadgen's fleet
    report uses."""
    from makisu_tpu.docker.image import ImageName
    from makisu_tpu.storage import ImageStore
    with ImageStore(storage) as store:
        manifest = store.manifests.load(ImageName.parse(tag))
        return [layer.digest.hex() for layer in manifest.layers]


class CanaryDriver:
    """Periodic synthetic builds through every alive worker.

    Each sweep builds one tiny generated context (reusing loadgen's
    template generator, so the content exercises the same base/src
    cache-node split real contexts do) directly against each alive
    worker with cooperative no-wait admission — a saturated or wedged
    worker answers with an immediate refusal instead of silently
    queueing canaries behind the fault, and a worker that accepts but
    stalls mid-build trips the bounded read timeout. Outcomes feed:

    - ``makisu_canary_builds_total{worker,result}`` and
      ``makisu_canary_latency_seconds{worker}``;
    - per-worker cumulative ``canary_total``/``canary_bad`` counters
      (a canary is *bad* when it fails OR exceeds ``slow_seconds``) —
      the ``build_latency_burn`` rule's inputs;
    - the EWMA health score pushed into the scheduler
      (``set_health_score``) for health-demoted routing;
    - cross-worker digest identity (healthy workers building the same
      context must produce byte-identical layers).
    """

    def __init__(self, scheduler, interval: float = 0.0,
                 timeout: float = 30.0, slow_seconds: float = 10.0,
                 work_dir: str = "", tenant: str = "_canary",
                 hasher: str = "cpu",
                 alpha: float = HEALTH_ALPHA) -> None:
        self.scheduler = scheduler
        self.interval = float(interval)
        self.timeout = float(timeout)
        self.slow_seconds = float(slow_seconds)
        self.tenant = tenant
        self.hasher = hasher
        self.alpha = float(alpha)
        self._cleanup = not work_dir
        self.work_dir = work_dir or tempfile.mkdtemp(
            prefix="makisu-canary-")
        self._ctx = os.path.join(self.work_dir, "ctx")
        self._mu = threading.Lock()
        self._totals: dict[str, int] = {}
        self._bads: dict[str, int] = {}
        self._scores: dict[str, float] = {}
        self._last: dict[str, dict] = {}
        self._digest_mismatch = False
        self._sweeps = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _ensure_context(self) -> None:
        if not os.path.isdir(os.path.join(self._ctx, "src")):
            from makisu_tpu.tools import loadgen
            os.makedirs(self._ctx, exist_ok=True)
            # Tiny and fixed-seed: 2 files × 1 KiB — enough to walk
            # the full path (context scan, chunking, layer commit,
            # manifest) without becoming load.
            loadgen._make_template(self._ctx, 0, files=2, file_kb=1)

    def sweep(self) -> None:
        """One canary round across every alive worker, in parallel —
        a wedged worker's bounded failure must not delay a healthy
        sibling's probe."""
        self._ensure_context()
        targets = self.scheduler.canary_targets()
        threads = []
        for worker_id, socket_path, storage in targets:
            # check: allow(ctx-propagation)
            t = threading.Thread(
                target=self._probe_worker,
                args=(worker_id, socket_path, storage),
                daemon=True, name=f"canary-{worker_id}")
            t.start()
            threads.append(t)
        deadline = time.monotonic() + self.timeout + 5.0
        for t in threads:
            t.join(max(deadline - time.monotonic(), 0.1))
        self._check_digests()
        with self._mu:
            self._sweeps += 1

    def _probe_worker(self, worker_id: str, socket_path: str,
                      storage: str | None) -> None:
        from makisu_tpu.worker.client import WorkerClient
        root = os.path.join(self.work_dir, f"root-{worker_id}")
        os.makedirs(root, exist_ok=True)
        tag = f"makisu-canary/{worker_id}:latest"
        argv = ["--log-level", "error", "build", self._ctx,
                "-t", tag, "--hasher", self.hasher, "--root", root]
        if storage:
            argv += ["--storage", storage]
        client = WorkerClient(socket_path, timeout=self.timeout,
                              connect_timeout=min(self.timeout, 5.0),
                              retries=0)
        t0 = time.monotonic()
        ok = False
        error = ""
        try:
            code = client.build(argv, tenant=self.tenant,
                                no_wait=True)
            ok = code == 0
            if not ok:
                error = f"exit {code}"
        except (OSError, RuntimeError,
                http.client.HTTPException) as exc:
            error = f"{type(exc).__name__}: {exc}"
        elapsed = time.monotonic() - t0
        digests: list[str] = []
        if ok and storage:
            try:
                digests = _canary_layer_digests(storage, tag)
            except Exception as exc:  # noqa: BLE001 - telemetry only
                log.debug("canary digest read failed for %s: %s",
                          worker_id, exc)
        bad = (not ok) or elapsed >= self.slow_seconds
        g = metrics.global_registry()
        g.counter_add(metrics.CANARY_BUILDS, worker=worker_id,
                      result="ok" if ok else "error")
        g.observe(metrics.CANARY_LATENCY, elapsed, worker=worker_id)
        with self._mu:
            self._totals[worker_id] = \
                self._totals.get(worker_id, 0) + 1
            self._bads[worker_id] = \
                self._bads.get(worker_id, 0) + (1 if bad else 0)
            prev = self._scores.get(worker_id, 1.0)
            score = ((1.0 - self.alpha) * prev
                     + self.alpha * (0.0 if bad else 1.0))
            self._scores[worker_id] = score
            self._last[worker_id] = {
                "ok": ok, "bad": bad,
                "latency_seconds": round(elapsed, 3),
                "error": error, "digests": digests,
                "ts": round(time.time(), 3),
            }
        # set_health_score also publishes makisu_worker_health_score.
        self.scheduler.set_health_score(worker_id, score)

    def _check_digests(self) -> None:
        """Healthy workers building the identical context must land on
        identical layer digests — divergence is a worker with corrupt
        cache/storage state, the exact failure canaries exist to
        catch."""
        with self._mu:
            digest_sets = {tuple(row["digests"])
                           for row in self._last.values()
                           if row.get("ok") and row.get("digests")}
            self._digest_mismatch = len(digest_sets) > 1

    # -- probe surfaces ---------------------------------------------------

    def counters(self) -> dict[str, dict[str, float]]:
        with self._mu:
            return {
                "canary_total": {k: float(v) for k, v
                                 in self._totals.items()},
                "canary_bad": {k: float(v) for k, v
                               in self._bads.items()},
            }

    def levels(self) -> dict[str, Any]:
        with self._mu:
            return {
                "canary_health_score": dict(self._scores),
                "canary_digest_mismatch":
                    1.0 if self._digest_mismatch else 0.0,
            }

    def status(self) -> dict[str, Any]:
        """Per-worker canary state for /alerts and the fleet vitals."""
        with self._mu:
            return {
                "sweeps": self._sweeps,
                "digest_mismatch": self._digest_mismatch,
                "workers": {
                    wid: {
                        "score": round(self._scores.get(wid, 1.0), 4),
                        "total": self._totals.get(wid, 0),
                        "bad": self._bads.get(wid, 0),
                        **self._last.get(wid, {}),
                    }
                    for wid in sorted(self._totals)
                },
            }

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "CanaryDriver":
        if self.interval <= 0 or self._thread is not None:
            return self
        # check: allow(ctx-propagation)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="canary-driver")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sweep()
            except Exception as exc:  # noqa: BLE001 - never dies
                log.debug("canary sweep failed: %s", exc)

    def stop(self) -> None:
        self._stop.set()
        if self._cleanup:
            shutil.rmtree(self.work_dir, ignore_errors=True)
