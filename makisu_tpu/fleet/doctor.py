"""``makisu-tpu doctor --fleet SOCKET``: cross-worker diagnosis.

The per-process forensics (``doctor BUNDLE``, ``doctor --device``)
explain one process. A fleet fails in the seams BETWEEN processes:
a worker the scheduler believes dead, a peer map a restarted worker
silently lost, a tenant pinned at its quota while the fleet idles,
a sticky placement pointing at a worker whose session evaporated.
This module reads the front door's ``/healthz`` (fleet + self
sections) and renders those seams as a diagnosis — pure functions of
the payload, so tests feed canned snapshots.
"""

from __future__ import annotations

# Alert severity → doctor finding severity (the two vocabularies
# predate each other: alerts page/warn/info, findings error/warning/
# info). Unknown alert severities map to warning — visible, not fatal.
_ALERT_SEVERITY = {"page": "error", "warn": "warning", "info": "info"}


def alert_findings(alerts: dict | None) -> list[dict]:
    """Findings from a ``GET /alerts`` payload (worker or fleet
    shape): every active alert becomes one finding, severity mapped
    through ``_ALERT_SEVERITY``. The fleet payload's per-worker
    sections contribute worker-tagged findings."""
    if not alerts:
        return []
    findings: list[dict] = []

    def add(active, worker: str = "") -> None:
        for a in active or []:
            name = a.get("rule", "?")
            if a.get("label"):
                name = f"{name}[{a['label']}]"
            detail = (f"alert {name} firing"
                      + (f" on worker {worker}" if worker else "")
                      + f": {a.get('message') or name}")
            if a.get("value") is not None \
                    and a.get("threshold") is not None:
                detail += (f" (value {a['value']:g} vs threshold "
                           f"{a['threshold']:g})")
            findings.append({
                "severity": _ALERT_SEVERITY.get(
                    str(a.get("severity")), "warning"),
                "kind": "alert",
                "rule": a.get("rule", "?"),
                "worker": worker,
                "detail": detail,
            })

    add(alerts.get("active"))
    for wid, payload in sorted((alerts.get("workers") or {}).items()):
        if isinstance(payload, dict):
            add(payload.get("active"), wid)
    return findings


def diagnose_fleet(health: dict,
                   alerts: dict | None = None) -> list[dict]:
    """Structured findings from a fleet front door's ``/healthz``
    payload (plus, when provided, its ``/alerts`` payload). Each
    finding: ``{"severity": "error"|"warning"|"info",
    "kind": ..., "detail": ...}``, most severe first."""
    findings: list[dict] = alert_findings(alerts)
    fleet = health.get("fleet") or {}
    self_section = health.get("self") or {}
    workers = fleet.get("workers") or []
    alive = [w for w in workers if w.get("alive")]

    # 1. Dead workers: the scheduler routes around them, but an
    # operator must know capacity is gone (and why the poll failed).
    for w in workers:
        if not w.get("alive"):
            age = w.get("last_poll_age_seconds")
            findings.append({
                "severity": "error",
                "kind": "dead_worker",
                "worker": w.get("id", "?"),
                "detail": f"worker {w.get('id', '?')} is DEAD "
                          f"({w.get('last_error') or 'no poll yet'}; "
                          f"last poll "
                          f"{age if age is not None else '?'}s ago, "
                          f"{w.get('consecutive_failures', 0)} "
                          f"consecutive failures) — capacity lost, "
                          f"its resident sessions will rebuild "
                          f"elsewhere cold",
            })
    # 1b. Per-worker alert digests from /healthz (poll-captured): when
    # the full /alerts payload wasn't fetched, the counts still name
    # which worker is paging.
    if alerts is None:
        for w in alive:
            digest = w.get("alerts") or {}
            active = int(digest.get("active", 0) or 0)
            if active:
                pages = int(digest.get("page", 0) or 0)
                findings.append({
                    "severity": "error" if pages else "warning",
                    "kind": "alert",
                    "worker": w.get("id", "?"),
                    "detail": f"worker {w.get('id', '?')} reports "
                              f"{active} active alert(s)"
                              f" ({pages} page) — `makisu-tpu alerts "
                              f"<socket>` for the rules",
                })
    # 2. Draining workers: deliberate, but worth naming (drain that
    # never concludes is an operator leak).
    for w in workers:
        if w.get("alive") and w.get("draining"):
            findings.append({
                "severity": "info",
                "kind": "draining_worker",
                "worker": w.get("id", "?"),
                "detail": f"worker {w.get('id', '?')} is draining "
                          f"({w.get('active_builds', 0)} builds "
                          f"still in flight; serving peer fetches)",
            })
    # 3. Stale peer maps: a worker holding (or acked at) a version
    # behind the scheduler's current one fetches chunks from a stale
    # membership — dead peers cost timeouts, new peers go unused.
    peer_map = self_section.get("peer_map") or {}
    version = peer_map.get("version",
                           fleet.get("peer_map_version", 0))
    acked = peer_map.get("acked") or {}
    for w in alive:
        wid = w.get("id", "?")
        held = acked.get(wid)
        if held is not None and held < version:
            findings.append({
                "severity": "warning",
                "kind": "stale_peer_map",
                "worker": wid,
                "detail": f"worker {wid} last acked peer map "
                          f"v{held} but the scheduler is at "
                          f"v{version} — its chunk exchange runs on "
                          f"stale membership until the next publish "
                          f"lands",
            })
    # 4. Quota starvation: a tenant pinned at its cap while builds
    # queue at the front door — the quota is doing its job, but a
    # persistently pinned tenant is a sizing signal.
    quota = int(fleet.get("tenant_quota", 0) or 0)
    waiting = int(fleet.get("frontdoor_waiting", 0) or 0)
    if quota > 0:
        for tenant, row in sorted((fleet.get("tenants")
                                   or {}).items()):
            if int(row.get("inflight", 0)) >= quota:
                findings.append({
                    "severity": "warning" if waiting else "info",
                    "kind": "quota_pinned",
                    "tenant": tenant,
                    "detail": f"tenant {tenant} is pinned at its "
                              f"quota ({row.get('inflight')}/{quota} "
                              f"in flight"
                              + (f"; {waiting} build(s) waiting at "
                                 f"the front door" if waiting
                                 else "") + ")",
                })
    # 5. Storage-plane findings: each worker's /healthz carries a
    # census digest (PR 16) — cached audit/scrub finding counts and
    # the chunk-CAS LRU-seed state. A worker reporting findings has
    # inconsistent content planes (dangling refs, orphaned twins,
    # scrub corruption); an unseeded LRU map means eviction decisions
    # there would be blind.
    for w in alive:
        wid = w.get("id", "?")
        storage = w.get("storage") or {}
        if not storage:
            continue
        s_findings = storage.get("findings") or {}
        total = int(s_findings.get("total", 0) or 0)
        if total:
            kinds = ", ".join(
                f"{kind}={count}" for kind, count in sorted(
                    (s_findings.get("kinds") or {}).items()))
            findings.append({
                "severity": "warning",
                "kind": "storage_findings",
                "worker": wid,
                "detail": f"worker {wid} reports {total} storage "
                          f"finding(s) ({kinds or 'unclassified'}) — "
                          f"run `makisu-tpu doctor --storage "
                          f"<socket>` against it for the object "
                          f"list",
            })
        seed = storage.get("lru_seed") or {}
        if seed.get("state") not in (None, "seeded"):
            findings.append({
                "severity": "info",
                "kind": "storage_unseeded",
                "worker": wid,
                "detail": f"worker {wid}'s chunk-CAS LRU map is "
                          f"{seed.get('state')} "
                          f"({seed.get('seeded_entries', 0)} "
                          f"entries seeded) — eviction dry-runs "
                          f"refuse until the seed completes",
            })
        # Budget digest (PR 20's content store): a worker far over
        # its hot-tier byte budget is one the evictor cannot keep up
        # with — routing demotes it (pressure_demoted) and the disk
        # will fill unless the budget, tiering, or load changes.
        budget = storage.get("budget") or {}
        pressure = float(budget.get("pressure", 0.0) or 0.0)
        if pressure >= 1.25:
            findings.append({
                "severity": "warning",
                "kind": "storage_pressure",
                "worker": wid,
                "detail": f"worker {wid}'s hot tier is at "
                          f"{100.0 * pressure:.0f}% of its storage "
                          f"budget ({budget.get('hot_bytes', 0)} of "
                          f"{budget.get('budget_bytes', 0)} bytes; "
                          f"{budget.get('evictions_total', 0)} "
                          f"evictions so far) — routing demotes it "
                          f"until eviction catches up",
            })
    # 5a. Continuous-profiling vitals: each worker's /healthz carries
    # its sampler digest. A sampler past its overhead budget is
    # charging builds for its own observation; dropped stacks mean the
    # bounded fold table overflowed and the profile under-reports.
    for w in alive:
        wid = w.get("id", "?")
        prof = w.get("profiler") or {}
        if not prof.get("enabled"):
            continue
        overhead = float(prof.get("overhead_fraction", 0.0) or 0.0)
        if overhead > 0.02:
            findings.append({
                "severity": "warning",
                "kind": "profiler_overhead",
                "worker": wid,
                "detail": f"worker {wid}'s profiler measures "
                          f"{100.0 * overhead:.1f}% overhead (budget "
                          f"2%) at {prof.get('hz', 0):g} Hz — lower "
                          f"MAKISU_TPU_PROFILE_HZ there",
            })
        dropped = int(prof.get("dropped", 0) or 0)
        if dropped:
            findings.append({
                "severity": "info",
                "kind": "profiler_dropped",
                "worker": wid,
                "detail": f"worker {wid}'s profiler dropped {dropped} "
                          f"sample(s) at its folded-stack cap — its "
                          f"profiles under-report the long tail",
            })
    # 5b. Session-snapshot restore failures: each worker's fleet row
    # carries the snapshot-plane digest captured from its /sessions
    # poll (write/restore tallies + the last restore failure). A
    # worker refusing restores is paying cold rebuilds the snapshot
    # plane exists to avoid — the reason names why (stale, isa_change,
    # flag_identity, chunks_unavailable, corrupt, ...).
    for w in alive:
        wid = w.get("id", "?")
        snap = w.get("session_snapshot") or {}
        failed = (int(snap.get("restore_refused", 0) or 0)
                  + int(snap.get("restore_error", 0) or 0))
        if not failed:
            continue
        last = snap.get("last_restore_failure") or {}
        reason = str(last.get("reason", "") or "unknown")
        context = str(last.get("context", "") or "")
        findings.append({
            "severity": "warning",
            "kind": "snapshot_restore_failed",
            "worker": wid,
            "detail": f"worker {wid} failed {failed} session-"
                      f"snapshot restore(s) (last: {reason}"
                      + (f" on {context}" if context else "")
                      + f"; {int(snap.get('restore', 0) or 0)} "
                      f"succeeded) — its builds rebuild cold "
                      f"instead of restoring warm",
        })
    # 6. Placement-memo drift: the sticky memo says a context lives
    # on worker X, but no alive worker — or a DIFFERENT one — reports
    # the resident session. Routing still works (the memo re-places),
    # but warm state is not where the scheduler thinks it is.
    sessions_of = {w.get("id"): set(w.get("sessions") or [])
                   for w in alive}
    for context, wid in sorted((fleet.get("placements")
                                or {}).items()):
        holders = sorted(w for w, sess in sessions_of.items()
                         if context in sess)
        if wid not in sessions_of:
            findings.append({
                "severity": "warning",
                "kind": "placement_drift",
                "worker": wid,
                "detail": f"placement memo pins {context} to "
                          f"{wid}, which is not alive"
                          + (f" (session actually resident on "
                             f"{', '.join(holders)})" if holders
                             else " (no resident session anywhere — "
                                  "next build is cold)"),
            })
        elif holders and wid not in holders:
            findings.append({
                "severity": "info",
                "kind": "placement_drift",
                "worker": wid,
                "detail": f"placement memo pins {context} to {wid} "
                          f"but the resident session is on "
                          f"{', '.join(holders)} — next build pays "
                          f"a relocation",
            })
    severity_rank = {"error": 0, "warning": 1, "info": 2}
    findings.sort(key=lambda f: severity_rank.get(f["severity"], 3))
    return findings


def render_fleet_doctor(health: dict, socket_path: str = "",
                        alerts: dict | None = None) -> str:
    """The human rendering: front-door vitals, the per-worker table,
    then the diagnosis (alert findings first when ``/alerts`` was
    fetched)."""
    fleet = health.get("fleet") or {}
    self_section = health.get("self") or {}
    workers = fleet.get("workers") or []
    lines = [
        "makisu-tpu fleet doctor"
        + (f" — {socket_path}" if socket_path else ""),
        f"front door: status {health.get('status', '?')}   "
        f"uptime {health.get('uptime_seconds', 0.0):.0f}s   "
        f"active {health.get('active_builds', 0)}   "
        f"queued {fleet.get('frontdoor_waiting', 0)}   "
        f"last progress "
        f"{health.get('last_progress_seconds', 0.0):.1f}s ago",
    ]
    peer_map = self_section.get("peer_map") or {}
    ring = self_section.get("decision_ring") or {}
    if self_section:
        oldest = self_section.get("oldest_poll_age_seconds")
        lines.append(
            f"self: poll every "
            f"{self_section.get('poll_interval_seconds', '?')}s "
            f"(oldest poll "
            f"{oldest if oldest is not None else '?'}s)   "
            f"peer map v{peer_map.get('version', '?')} "
            f"({len(peer_map.get('stale_acks') or [])} stale ack(s))"
            f"   decisions rung {ring.get('size', 0)} "
            + " ".join(f"{k}={v}" for k, v in sorted(
                (ring.get('verdicts') or {}).items()))
            + f"   watchdog "
            + ("armed" if self_section.get("watchdog_armed")
               else "off"))
    lines.append("")
    from makisu_tpu.utils import traceexport
    lines.append(f"{'WORKER':<8s} {'STATE':<9s} {'ACTIVE':>6s} "
                 f"{'QUEUE':>6s} {'SESS':>5s} {'PEERMAP':>8s} "
                 f"{'STORAGE':>8s}  LAST ERROR")
    acked = peer_map.get("acked") or {}
    for w in workers:
        wid = w.get("id", "?")
        held = acked.get(wid)
        storage = w.get("storage") or {}
        stor = (traceexport.fmt_bytes(storage.get("total_bytes", 0))
                if storage else "-")
        lines.append(
            f"{wid:<8s} {w.get('state', '?'):<9s} "
            f"{w.get('active_builds', 0):>6d} "
            f"{w.get('queue_depth', 0):>6d} "
            f"{len(w.get('sessions') or []):>5d} "
            f"{('v' + str(held)) if held is not None else '-':>8s} "
            f"{stor:>8s}  "
            f"{w.get('last_error') or '-'}")
    findings = diagnose_fleet(health, alerts)
    lines.append("")
    if not findings:
        lines.append("diagnosis: fleet healthy — no findings")
    else:
        lines.append(f"diagnosis ({len(findings)} finding(s)):")
        for f in findings:
            lines.append(f"  [{f['severity']:<7s}] {f['detail']}")
    return "\n".join(lines) + "\n"
