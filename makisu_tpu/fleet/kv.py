"""Shared cache-KV endpoint for fleet harnesses.

A fleet is only a fleet when its workers share a cache plane: worker B
must be able to HIT the KV entry worker A wrote for the same context —
that is what makes B's chunk CAS consult its peers (and then the
registry) instead of rebuilding from scratch. Production deployments
bring their own (``--redis-cache-addr`` / ``--http-cache-addr``
against a real service); loadgen ``--fleet``, the fleet tests, and the
CI fleet smoke use THIS: a minimal in-process HTTP server speaking
exactly the wire protocol ``cache/kv.py HTTPStore`` already consumes
(``GET /<key>`` → 200 value | 404, ``PUT /<key>`` → 200), backed by a
dict. Loopback TCP because HTTPStore dials ``host:port``.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _KVHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *args) -> None:  # quiet
        pass

    def do_GET(self) -> None:
        value = self.server.kv_get(self.path.lstrip("/"))
        if value is None:
            self._respond(404, b"")
            return
        self._respond(200, value.encode())

    def do_PUT(self) -> None:
        length = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(length).decode()
        self.server.kv_put(self.path.lstrip("/"), body)
        self._respond(200, b"ok")

    def _respond(self, status: int, body: bytes) -> None:
        self.send_response(status)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)


class SharedKVServer(ThreadingHTTPServer):
    """``start()`` returns the ``host:port`` address to pass as every
    worker build's ``--http-cache-addr``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__((host, port), _KVHandler)
        self._data: dict[str, str] = {}
        self._mu = threading.Lock()
        self._thread: threading.Thread | None = None

    def kv_get(self, key: str) -> str | None:
        with self._mu:
            return self._data.get(key)

    def kv_put(self, key: str, value: str) -> None:
        with self._mu:
            self._data[key] = value

    def entry_count(self) -> int:
        with self._mu:
            return len(self._data)

    @property
    def address(self) -> str:
        host, port = self.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> str:
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True,
                                        name="fleet-shared-kv")
        self._thread.start()
        return self.address

    def stop(self) -> None:
        self.shutdown()
        self.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
