"""Peer chunk exchange: fetch missing chunks worker-to-worker.

The scheduler publishes the fleet membership to every worker
(``POST /peers``); this module holds that map process-wide and serves
the consuming side: when a build's chunk CAS is missing chunks that a
KV cache hit references (``cache/chunks.py ensure_available``), the
peers are consulted — ``GET /chunks/<fingerprint>`` on each worker
socket — BEFORE the registry/KV blob plane is paid. A sibling worker
that built the same (or any chunk-sharing) context holds the bytes one
unix-socket round trip away; the registry is a WAN away.

The exchange is **pack-granular** (ROADMAP item 1's named follow-up):
a fetch first asks each peer for the layer's signed recipe
(``GET /recipes/<layer_hex>`` — the distribution plane's metadata,
makisu_tpu/serve/) and pulls the missing chunks as coalesced ranged
pack reads (``GET /packs/<hex>`` with Range), so after a 1% edit the
peer wire carries ~the novel-region count in round trips instead of
one request per ~8KiB chunk. The per-chunk ``GET /chunks/<fp>`` route
is kept strictly as the fallback — old workers without the serve
endpoints, and chunks no published recipe covers. Both routes are
digest-verified on arrival and charged against the transfer engine's
memory budget so peer traffic and registry traffic share one bound.

In-process fleets (loadgen ``--fleet``, tests) share this module's
globals across their workers; that is correct — they also share one
peer map in a real deployment — except for self-identity, which is
context-bound per build (``bind_self_socket``) so a worker never pays
a round trip asking itself.
"""

from __future__ import annotations

import contextvars
import hashlib
import http.client
import threading

from makisu_tpu.utils import logging as log
from makisu_tpu.utils import metrics

# Metric names: the shared set in utils/metrics.py (hits/misses count
# CHUNKS, not requests).
PEER_CHUNK_HITS = metrics.FLEET_PEER_CHUNK_HITS
PEER_CHUNK_MISSES = metrics.FLEET_PEER_CHUNK_MISSES
PEER_CHUNK_BYTES = metrics.FLEET_PEER_CHUNK_BYTES
PEER_MAP_VERSION = metrics.FLEET_PEER_MAP_VERSION

# Connect/read timeout for one peer GET. Peers are local-ish sockets;
# a peer that can't answer in this window is effectively down and the
# registry fallback is waiting.
PEER_TIMEOUT = 5.0

# A peer that failed a request is skipped for this many seconds — a
# dead worker must not charge every subsequent missing chunk a
# connect timeout each.
PEER_BACKOFF = 10.0

_mu = threading.Lock()
_peers: tuple[str, ...] = ()
_version = 0
_dead_until: dict[str, float] = {}

# The requesting worker's own socket, bound per build context by
# WorkerServer.run_build: excluded from fetch attempts.
_self_socket: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "makisu_fleet_self_socket", default="")


def bind_self_socket(socket_path: str):
    """Mark ``socket_path`` as "myself" in the current context (a
    worker binds this around each build so peer fetches skip it).
    Returns a reset token."""
    return _self_socket.set(socket_path)


def reset_self_socket(token) -> None:
    _self_socket.reset(token)


def set_peers(sockets, version: int | None = None) -> bool:
    """Install the peer map (the scheduler's ``POST /peers`` payload).
    Versions are monotonic — a late-arriving stale map is ignored.
    Returns whether the map was applied."""
    global _peers, _version
    cleaned = tuple(dict.fromkeys(s for s in sockets if s))
    with _mu:
        if version is not None and version < _version:
            return False
        _peers = cleaned
        if version is not None:
            _version = version
        else:
            _version += 1
        _dead_until.clear()
        metrics.global_registry().gauge_set(PEER_MAP_VERSION, _version)
    return True


def peers() -> tuple[str, ...]:
    with _mu:
        return _peers


def map_version() -> int:
    with _mu:
        return _version


def available() -> bool:
    """Whether any peer other than ourselves is known."""
    me = _self_socket.get()
    with _mu:
        return any(p != me for p in _peers)


def reset() -> None:
    """Drop the map (tests)."""
    global _peers, _version
    with _mu:
        _peers = ()
        _version = 0
        _dead_until.clear()


def _candidates(rotation: int) -> list[str]:
    """Live peers, self excluded, rotated so concurrent fetchers
    spread load instead of hammering the first listed worker."""
    import time
    me = _self_socket.get()
    now = time.monotonic()
    with _mu:
        live = [p for p in _peers
                if p != me and _dead_until.get(p, 0.0) <= now]
    if not live:
        return []
    pivot = rotation % len(live)
    return live[pivot:] + live[:pivot]


def _mark_dead(socket_path: str) -> None:
    import time
    with _mu:
        _dead_until[socket_path] = time.monotonic() + PEER_BACKOFF


def fetch_chunk(hex_digest: str) -> bytes | None:
    """Fetch one chunk from the first peer holding it; bytes are
    digest-verified before they are returned (a peer can be wrong, the
    CAS must not be). Returns None when no peer has it."""
    # Late import: worker.client imports nothing from the cache tree,
    # but keeping it out of module import time keeps peers importable
    # from anywhere in the tree without cycles.
    from makisu_tpu.worker.client import _UnixHTTPConnection
    rotation = int(hex_digest[:8], 16) if len(hex_digest) >= 8 else 0
    for peer in _candidates(rotation):
        conn = _UnixHTTPConnection(peer, PEER_TIMEOUT,
                                   connect_timeout=PEER_TIMEOUT)
        try:
            # The fetching build's trace context rides along so the
            # serving worker's access ledger names this build's trace.
            conn.request("GET", f"/chunks/{hex_digest}", headers={
                "traceparent": metrics.current_traceparent()})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                continue
            if hashlib.sha256(data).hexdigest() != hex_digest:
                log.warning("peer %s served corrupt chunk %s",
                            peer, hex_digest)
                continue
            return data
        except (OSError, http.client.HTTPException):
            _mark_dead(peer)
            continue
        finally:
            conn.close()
    return None


def fetch_via_packs(put, missing: list[str],
                    layer_hex: str) -> set[str]:
    """Pack-granular exchange: ask each live peer for the layer's
    signed recipe; a peer that answers serves the missing chunks as
    coalesced ranged pack reads through the shared planning/fetch core
    (serve/client.py — per-run budget reservations, digest-verified
    carving). Peers that 404 (old workers, or the layer just isn't
    published there) cost one round trip and fall through; remaining
    chunks go to the per-chunk fallback. Returns the digests
    obtained."""
    from makisu_tpu.serve.client import ServeClient, fetch_missing
    want = set(missing)
    got: set[str] = set()
    rotation = int(layer_hex[:8], 16) if len(layer_hex) >= 8 else 0
    for peer in _candidates(rotation):
        if not want:
            break
        client = ServeClient(peer, timeout=PEER_TIMEOUT,
                             connect_timeout=PEER_TIMEOUT)
        doc = client.recipe(layer_hex)
        if doc is None:
            if client.transport_failures:
                # Dead/wedged peer (not a 404): back it off like the
                # per-chunk route does, instead of re-paying the
                # timeout on every later layer.
                _mark_dead(peer)
            continue
        covered = {row[0] for row in doc["chunks"]} & want
        if not covered:
            continue
        # The peer wire rides the same seekable-zstd frames as the
        # serve plane when the recipe advertises them (zpacks) — a
        # relocated build's chunks cross worker sockets compressed;
        # old peers without /zpacks 404 back onto the raw pack wire.
        from_peer, stats = fetch_missing(client.pack_range,
                                         doc["chunks"], covered, put,
                                         pack_sizes=doc.get("packs"),
                                         zframes=doc.get("zpacks"),
                                         fetch_zrange=client.zpack_range)
        if client.transport_failures:
            _mark_dead(peer)
        if stats["requests"]:
            metrics.counter_add(metrics.SERVE_PEER_PACK_REQUESTS,
                                stats["requests"])
            metrics.counter_add(metrics.SERVE_PEER_PACK_BYTES,
                                stats["bytes_fetched"])
        if from_peer:
            log.info("fetched %d/%d missing chunks from peer %s as "
                     "%d ranged pack read(s)", len(from_peer),
                     len(want), peer, stats["requests"])
        got |= from_peer
        want -= from_peer
    return got


def fetch_chunks(put, missing: list[str],
                 lengths: dict[str, int],
                 layer_hex: str | None = None) -> set[str]:
    """Fetch ``missing`` chunks from peers: pack-granular first when
    the caller can name the layer (``layer_hex`` — recipes are keyed
    by it), then the per-chunk fallback in parallel on the transfer
    engine (blob-granular leaves, like the registry chunk fetches they
    stand in front of), each reservation charged to the global memory
    budget. ``put(hex, bytes)`` stores a verified chunk (ChunkStore.put
    re-verifies; cheap). Returns the digests obtained."""
    if not missing or not available():
        return set()
    requested = len(missing)
    got: set[str] = set()
    got_bytes = [0]
    if layer_hex:
        got = fetch_via_packs(put, missing, layer_hex)
        got_bytes[0] = sum(lengths.get(h, 0) for h in got)
        missing = [h for h in missing if h not in got]
    from makisu_tpu.registry import transfer
    engine = transfer.engine()
    mu = threading.Lock()

    def fetch_one(hex_digest: str) -> None:
        with engine.budget.reserve(lengths.get(hex_digest, 0)):
            data = fetch_chunk(hex_digest)
            if data is None:
                return
            try:
                put(hex_digest, data)
            except (ValueError, OSError) as e:
                log.warning("peer chunk %s unusable: %s",
                            hex_digest, e)
                return
        with mu:
            got.add(hex_digest)
            got_bytes[0] += len(data)

    if missing:
        engine.map(fetch_one, missing)
    if got:
        metrics.counter_add(PEER_CHUNK_HITS, len(got))
        metrics.counter_add(PEER_CHUNK_BYTES, got_bytes[0])
    if requested > len(got):
        metrics.counter_add(PEER_CHUNK_MISSES, requested - len(got))
    return got
