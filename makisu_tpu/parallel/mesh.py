"""Device-mesh helpers for the hashing pipeline.

The mesh has two axes:
- ``data``: independent byte buffers / lane groups (pure data parallel).
- ``seq``: the long-stream dimension of one buffer, sharded with a
  Gear-window halo exchanged over ICI (parallel/pipeline.py) — this
  system's sequence-parallel axis (SURVEY.md §5).

Multi-host scale-out follows the same recipe: jax.distributed initializes
processes, the mesh spans all devices, and XLA routes the halo ppermute
over ICI/DCN. No hand-rolled communication anywhere.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
SEQ_AXIS = "seq"


def make_mesh(devices=None, seq_parallel: int | None = None) -> Mesh:
    """Build a (data, seq) mesh over the available devices.

    ``seq_parallel`` fixes the seq-axis size; by default the mesh is
    as square as possible with seq >= data (halo traffic is cheap, so
    favor splitting the long dimension).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if seq_parallel is None:
        seq_parallel = 1
        for cand in range(int(np.sqrt(n)), n + 1):
            if n % cand == 0:
                seq_parallel = cand
                break
    if n % seq_parallel:
        raise ValueError(f"{n} devices not divisible by seq={seq_parallel}")
    arr = np.array(devices).reshape(n // seq_parallel, seq_parallel)
    return Mesh(arr, (DATA_AXIS, SEQ_AXIS))


def block_sharding(mesh: Mesh) -> NamedSharding:
    """[B, N] byte blocks: batch over data, stream over seq."""
    return NamedSharding(mesh, PartitionSpec(DATA_AXIS, SEQ_AXIS))


def lane_sharding(mesh: Mesh) -> NamedSharding:
    """[L, CAP] chunk lanes: lanes over every device."""
    return NamedSharding(mesh, PartitionSpec((DATA_AXIS, SEQ_AXIS), None))


def lane_vec_sharding(mesh: Mesh) -> NamedSharding:
    """[L] per-lane scalars, matching lane_sharding's first axis."""
    return NamedSharding(mesh, PartitionSpec((DATA_AXIS, SEQ_AXIS)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
