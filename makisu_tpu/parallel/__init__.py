"""Multi-chip parallelism for the hashing pipeline (mesh + shardings +
halo-stitched kernels)."""

from makisu_tpu.parallel.mesh import (
    DATA_AXIS,
    SEQ_AXIS,
    block_sharding,
    lane_sharding,
    lane_vec_sharding,
    make_mesh,
    replicated,
)
from makisu_tpu.parallel.pipeline import (
    gear_bitmap_sharded,
    sha256_lanes_sharded,
    snapshot_hash_step,
)

__all__ = [
    "DATA_AXIS", "SEQ_AXIS", "block_sharding", "lane_sharding",
    "lane_vec_sharding", "make_mesh", "replicated",
    "gear_bitmap_sharded", "sha256_lanes_sharded", "snapshot_hash_step",
]
