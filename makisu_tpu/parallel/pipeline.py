"""Mesh-sharded hashing pipeline: the multi-chip form of ops/gear +
ops/sha256.

The long-stream dimension is genuinely sequence-parallel: Gear's hash at
position i depends on at most the 31 previous bytes (mod 2^32 window), so
a shard only needs a 31-byte (WINDOW-1) halo from its left neighbor —
one ``lax.ppermute`` over ICI per scan, the cheapest possible collective.
This is the project's ring-attention analogue (SURVEY.md §5): where the
reference hashes a layer as one sequential CPU stream
(lib/builder/step/common.go:35-67), here the stream splits across chips
with exact boundary stitching.

Chunk-lane SHA-256 is embarrassingly parallel over lanes; sharding the
lane axis over the whole mesh needs no collectives at all.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from makisu_tpu.ops import gear, sha256
from makisu_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS


def _gear_bitmap_local(block: jax.Array, axis_name: str,
                       avg_bits: int) -> jax.Array:
    """Per-shard candidate bitmap with a left-neighbor halo over
    ``axis_name``. One evaluation per shard: the neighbor's last 31
    bytes arrive by ppermute, their G-VALUES seed the windowed sum
    (masked to zero on shard 0, whose stream starts cold) — the same
    halo mechanism the blocked scan uses between 64KiB blocks, so each
    shard also gets the bandwidth-lean path when its local size allows.
    """
    n_shards = jax.lax.psum(1, axis_name)
    halo_bytes = jax.lax.ppermute(
        block[..., -(gear.WINDOW - 1):], axis_name,
        perm=[(i, (i + 1) % n_shards) for i in range(n_shards)])
    halo_g = gear._gear_value(halo_bytes)
    # Shard 0 has no left history: zero G-halo == the zero-history
    # start convention (zero-valued halo BYTES would not be: G[0] != 0).
    is_first = jax.lax.axis_index(axis_name) == 0
    halo_g = jnp.where(is_first, jnp.uint32(0), halo_g)
    return gear.gear_bitmap_with_halo(block, halo_g, avg_bits)


def gear_bitmap_sharded(mesh: Mesh, avg_bits: int = gear.DEFAULT_AVG_BITS):
    """Jitted [B, N] uint8 → [B, N//32] uint32 candidate bitmap, with B
    over the data axis and N over the seq axis (halo-stitched)."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=P(DATA_AXIS, SEQ_AXIS),
        out_specs=P(DATA_AXIS, SEQ_AXIS))
    def _shard(block):
        return _gear_bitmap_local(block, SEQ_AXIS, avg_bits)

    return jax.jit(_shard)


def _mark_varying(x: jax.Array, axes: tuple[str, ...]) -> jax.Array:
    """Mark a replicated constant as device-varying for shard_map's
    per-axis typing. The API moved across jax releases — ``pcast``
    (typing prototype) → ``pvary`` (0.6+) — and older releases have no
    varying-ness typing at all, where the value is correct as-is."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axes)
    return x


def sha256_lanes_sharded(mesh: Mesh):
    """Jitted ragged-lane SHA-256 with lanes spread over every device."""
    lanes_spec = P((DATA_AXIS, SEQ_AXIS), None)
    vec_spec = P((DATA_AXIS, SEQ_AXIS))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(lanes_spec, vec_spec),
        out_specs=P((DATA_AXIS, SEQ_AXIS), None))
    def _shard(data, lengths):
        # Fused block-scan path (padding/packing inside the scan step),
        # same as single-chip. The scan carry must be device-varying
        # like the data (shard_map typing); mark the constant IV
        # accordingly.
        state0 = jnp.broadcast_to(jnp.asarray(sha256._H0)[:, None],
                                  (8, data.shape[0]))
        state0 = _mark_varying(state0, (DATA_AXIS, SEQ_AXIS))
        return sha256.sha256_lanes_impl(data, lengths, init_state=state0)

    return jax.jit(_shard)


def snapshot_hash_step(mesh: Mesh, avg_bits: int = gear.DEFAULT_AVG_BITS):
    """The full sharded "step": gear-scan a batch of stream blocks AND
    hash a batch of chunk lanes in one compiled program.

    blocks:  uint8 [B, N]    (B % data-axis == 0, N % (32*seq-axis) == 0)
    lanes:   uint8 [L, CAP]  (L % device-count == 0, CAP % 64 == 0)
    lengths: int32 [L]
    Returns (bitmap uint32 [B, N//32], digests uint32 [L, 8]).
    """
    gear_fn = gear_bitmap_sharded(mesh, avg_bits)
    sha_fn = sha256_lanes_sharded(mesh)

    def step(blocks, lanes, lengths):
        return gear_fn(blocks), sha_fn(lanes, lengths)

    return jax.jit(step)
