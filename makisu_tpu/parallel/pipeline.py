"""Mesh-sharded hashing pipeline: the multi-chip form of ops/gear +
ops/sha256.

The long-stream dimension is genuinely sequence-parallel: Gear's hash at
position i depends on at most the 31 previous bytes (mod 2^32 window), so
a shard only needs a WINDOW-byte halo from its left neighbor —
one ``lax.ppermute`` over ICI per scan, the cheapest possible collective.
This is the project's ring-attention analogue (SURVEY.md §5): where the
reference hashes a layer as one sequential CPU stream
(lib/builder/step/common.go:35-67), here the stream splits across chips
with exact boundary stitching.

Chunk-lane SHA-256 is embarrassingly parallel over lanes; sharding the
lane axis over the whole mesh needs no collectives at all.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from makisu_tpu.ops import gear, sha256
from makisu_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS


def _gear_local(block: jax.Array, axis_name: str) -> jax.Array:
    """Per-shard gear hashes with a left-neighbor halo over ``axis_name``.

    block: uint8 [..., n_local]; returns uint32 [..., n_local].
    """
    n_shards = jax.lax.psum(1, axis_name)
    halo = jax.lax.ppermute(
        block[..., -gear.WINDOW:], axis_name,
        perm=[(i, (i + 1) % n_shards) for i in range(n_shards)])
    ext = jnp.concatenate([halo, block], axis=-1)
    h_with_halo = gear.gear_hash(ext)[..., gear.WINDOW:]
    # Shard 0 has no left history: its hashes must treat the stream as
    # starting at its first byte (zero history != zero-valued halo bytes).
    h_start = gear.gear_hash(block)
    is_first = jax.lax.axis_index(axis_name) == 0
    return jnp.where(is_first, h_start, h_with_halo)


def gear_bitmap_sharded(mesh: Mesh, avg_bits: int = gear.DEFAULT_AVG_BITS):
    """Jitted [B, N] uint8 → [B, N//32] uint32 candidate bitmap, with B
    over the data axis and N over the seq axis (halo-stitched)."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=P(DATA_AXIS, SEQ_AXIS),
        out_specs=P(DATA_AXIS, SEQ_AXIS))
    def _shard(block):
        h = _gear_local(block, SEQ_AXIS)
        return gear.pack_bits(gear.boundary_mask(h, avg_bits))

    return jax.jit(_shard)


def sha256_lanes_sharded(mesh: Mesh):
    """Jitted ragged-lane SHA-256 with lanes spread over every device."""
    lanes_spec = P((DATA_AXIS, SEQ_AXIS), None)
    vec_spec = P((DATA_AXIS, SEQ_AXIS))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(lanes_spec, vec_spec),
        out_specs=P((DATA_AXIS, SEQ_AXIS), None))
    def _shard(data, lengths):
        msg = sha256.pad_lanes(data, lengths)
        # The scan carry must be device-varying like the data (shard_map
        # typing); mark the constant IV accordingly.
        state0 = jnp.broadcast_to(jnp.asarray(sha256._H0)[:, None],
                                  (8, data.shape[0]))
        state0 = jax.lax.pcast(state0, (DATA_AXIS, SEQ_AXIS), to="varying")
        return sha256.sha256_words(sha256.bytes_to_words(msg),
                                   sha256.num_blocks(lengths),
                                   init_state=state0)

    return jax.jit(_shard)


def snapshot_hash_step(mesh: Mesh, avg_bits: int = gear.DEFAULT_AVG_BITS):
    """The full sharded "step": gear-scan a batch of stream blocks AND
    hash a batch of chunk lanes in one compiled program.

    blocks:  uint8 [B, N]    (B % data-axis == 0, N % (32*seq-axis) == 0)
    lanes:   uint8 [L, CAP]  (L % device-count == 0, CAP % 64 == 0)
    lengths: int32 [L]
    Returns (bitmap uint32 [B, N//32], digests uint32 [L, 8]).
    """
    gear_fn = gear_bitmap_sharded(mesh, avg_bits)
    sha_fn = sha256_lanes_sharded(mesh)

    def step(blocks, lanes, lengths):
        return gear_fn(blocks), sha_fn(lanes, lengths)

    return jax.jit(step)
