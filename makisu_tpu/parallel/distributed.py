"""Multi-host initialization for the hashing mesh.

Single-host meshes need nothing; across hosts, JAX's distributed runtime
brings every process's devices into one global mesh, and the same
``(data, seq)`` shardings from parallel/mesh.py apply — XLA routes the
Gear-halo ppermute over ICI within a slice and DCN across slices. This is
the whole multi-host communication story: no hand-rolled backend
(SURVEY.md §5 "distributed communication backend" mapping).

Environment-driven (k8s-friendly), mirroring jax.distributed defaults:
  MAKISU_TPU_COORDINATOR   host:port of process 0
  MAKISU_TPU_NUM_PROCESSES total process count
  MAKISU_TPU_PROCESS_ID    this process's index
"""

from __future__ import annotations

import os

from makisu_tpu.utils import logging as log

_initialized = False


def initialize_multihost() -> bool:
    """Initialize jax.distributed from the environment; returns True if a
    multi-host setup was configured (False = single-host, no-op)."""
    global _initialized
    if _initialized:
        return True
    coordinator = os.environ.get("MAKISU_TPU_COORDINATOR", "")
    if not coordinator:
        return False
    import jax
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=int(os.environ["MAKISU_TPU_NUM_PROCESSES"]),
        process_id=int(os.environ["MAKISU_TPU_PROCESS_ID"]))
    _initialized = True
    log.info("joined distributed mesh",
             process=os.environ["MAKISU_TPU_PROCESS_ID"],
             processes=os.environ["MAKISU_TPU_NUM_PROCESSES"])
    return True
