"""The six `makisu-tpu check` rules, each distilled from a shipped bug.

Every rule names the PR whose review caught its bug class by hand; the
rule exists so the next instance fails CI instead of waiting for a
reviewer to remember. docs/ANALYSIS.md carries the full catalog and the
pragma/baseline workflow.
"""

from __future__ import annotations

import ast

from makisu_tpu.analysis.engine import (FileContext, Finding, Rule,
                                        call_name, expr_text,
                                        keyword_arg, last_attr)


def _file_is(ctx: FileContext, *suffixes: str) -> bool:
    return any(ctx.path.endswith(s) for s in suffixes)


class CtxPropagationRule(Rule):
    """PR 2's bug class: pool/thread work spawned without the caller's
    contextvars loses the build's telemetry registry and log sink —
    its spans/counters land in the process-global registry and
    concurrent worker builds mix. Every thread spawn must go through
    ``contextvars.copy_context().run`` (or the ``utils/concurrency``
    wrappers, which do it internally)."""

    name = "ctx-propagation"
    description = ("threading.Thread / pool .submit outside "
                   "utils/concurrency must carry contextvars via "
                   "copy_context().run")

    # Files that ARE the sanctioned wrappers (they implement the
    # propagation the rule enforces everywhere else).
    _EXEMPT = ("utils/concurrency.py", "registry/transfer.py")

    def collect(self, ctx: FileContext) -> list[Finding]:
        if _file_is(ctx, *self._EXEMPT):
            return []
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in ("threading.Thread", "Thread"):
                target = keyword_arg(node, "target")
                if target is None:
                    continue  # subclass style; run() overrides carry
                if not (isinstance(target, ast.Attribute)
                        and target.attr == "run"):
                    out.append(ctx.finding(
                        self.name, node,
                        "threading.Thread target does not ride a "
                        "copied context; use target=contextvars."
                        "copy_context().run (or a utils/concurrency "
                        "wrapper) so the build's telemetry registry "
                        "and log sink follow the thread"))
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "submit"):
                recv = expr_text(node.func.value).lower()
                if "pool" not in recv and "executor" not in recv:
                    continue  # not an executor-shaped receiver
                first = node.args[0] if node.args else None
                if (isinstance(first, ast.Attribute)
                        and first.attr == "run"):
                    continue  # submit(ctx.run, fn, ...)
                out.append(ctx.finding(
                    self.name, node,
                    "pool .submit without context propagation; use "
                    "concurrency.submit_ctx / ctx_map, or pass "
                    "copy_context().run as the callable"))
        return out


class SignalSafetyRule(Rule):
    """PR 4's review-fix class: the flight recorder's dump path runs
    inside SIGTERM/SIGUSR1 handlers, where the interrupted frame may
    hold any lock in the process — a timeout-less ``Lock.acquire`` (or
    a ``with lock:``) deadlocks the dying process, and logging both
    allocates and takes the logging module's own locks. This rule walks
    call-graph reachability from the actual handler installs (every
    function passed to ``signal.signal``) plus ``FlightRecorder.dump``
    and flags those operations in reachable code.

    Resolution is name-based and deliberately conservative: an
    attribute call resolves only when its name has at most
    ``_MAX_DEFS`` definitions repo-wide and does not shadow a builtin
    (a method named ``open`` must not wire its class into the signal
    set every time the dump path opens a file), so ubiquitous names
    never drag unrelated code in."""

    name = "signal-safety"
    description = ("code reachable from signal handlers / "
                   "flightrecorder.dump must not block on timeout-less "
                   "locks or log")

    _MAX_DEFS = 3
    _LOG_RECEIVERS = ("log", "logging")
    _LOG_LEVELS = {"debug", "info", "warning", "warn", "error",
                   "exception", "critical"}

    def __init__(self) -> None:
        # name -> list of (qualname, file ctx); qualname -> callee names
        self._defs: dict[str, list[tuple[str, FileContext]]] = {}
        self._edges: dict[str, set[str]] = {}
        # qualname -> potential violations [(Finding-ready args)]
        self._hazards: dict[str, list[tuple[FileContext, ast.AST,
                                            str]]] = {}
        self._roots: set[str] = set()

    def collect(self, ctx: FileContext) -> list[Finding]:
        module = ctx.path[:-3].replace("/", ".")
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # The def-count suffix keeps same-named definitions
                # (module-level wrapper + method, re-defs) from
                # overwriting each other's edges/hazards; BFS resolves
                # by NAME, so every definition still participates.
                seq = len(self._defs.setdefault(node.name, []))
                qual = f"{module}:{node.name}#{seq}"
                self._defs[node.name].append((qual, ctx))
                callees, hazards = self._scan_body(node, ctx)
                self._edges[qual] = callees
                self._hazards[qual] = hazards
            elif isinstance(node, ast.Call):
                self._note_root(node)
        # The issue's named seed: the flight recorder's dump entry.
        if _file_is(ctx, "utils/flightrecorder.py"):
            self._roots.add("dump")
        return []

    def _note_root(self, node: ast.Call) -> None:
        if call_name(node) not in ("signal.signal", "signal"):
            return
        if len(node.args) < 2:
            return
        handler = node.args[1]
        if isinstance(handler, ast.Name):
            self._roots.add(handler.id)
        elif isinstance(handler, ast.Lambda):
            for sub in ast.walk(handler.body):
                if isinstance(sub, ast.Call):
                    name = last_attr(sub)
                    if name:
                        self._roots.add(name)

    @staticmethod
    def _own_body(func: ast.AST):
        """Walk a function's OWN statements, stopping at nested
        def/lambda boundaries: a closure's hazards belong to the
        closure (collected as its own definition), not to every
        enclosing function — otherwise a pool-only worker closure
        gets flagged as signal-reachable through its parent."""
        stack = list(ast.iter_child_nodes(func))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(node))

    def _scan_body(self, func: ast.AST, ctx: FileContext
                   ) -> tuple[set[str], list]:
        callees: set[str] = set()
        hazards: list = []
        for node in self._own_body(func):
            if isinstance(node, ast.Call):
                name = last_attr(node)
                if name:
                    callees.add(name)
                hazard = self._call_hazard(node)
                if hazard:
                    hazards.append((ctx, node, hazard))
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    text = expr_text(item.context_expr).lower()
                    if "lock" in text and ".acquire" not in text:
                        hazards.append((
                            ctx, node,
                            f"`with {expr_text(item.context_expr)}` is "
                            f"a timeout-less lock acquire"))
        return callees, hazards

    def _call_hazard(self, node: ast.Call) -> str | None:
        if isinstance(node.func, ast.Attribute):
            recv = expr_text(node.func.value)
            if (node.func.attr == "acquire" and "lock" in recv.lower()
                    and not node.args and not node.keywords):
                return (f"timeout-less {recv}.acquire() — probe with "
                        f"acquire(timeout=...) and skip on failure")
            if (isinstance(node.func.value, ast.Name)
                    and node.func.value.id in self._LOG_RECEIVERS
                    and node.func.attr in self._LOG_LEVELS):
                return (f"logging call ({recv}.{node.func.attr}) — "
                        f"logging allocates and takes the log sink's "
                        f"locks")
        return None

    def finalize(self) -> list[Finding]:
        # BFS over name-resolved edges from the handler roots.
        reachable: dict[str, str] = {}  # qualname -> via-path
        frontier: list[tuple[str, str]] = []
        for root in sorted(self._roots):
            for qual, _ctx in self._defs.get(root, []):
                if qual not in reachable:
                    reachable[qual] = root
                    frontier.append((qual, root))
        import builtins
        shadowed = set(dir(builtins))
        while frontier:
            qual, path = frontier.pop()
            for callee in sorted(self._edges.get(qual, ())):
                if callee in shadowed:
                    continue  # `open`, `print`, ...: almost certainly
                    # the builtin, not the same-named repo method
                defs = self._defs.get(callee, [])
                if not defs or len(defs) > self._MAX_DEFS:
                    continue
                for cqual, _ctx in defs:
                    if cqual not in reachable:
                        via = f"{path} -> {callee}"
                        reachable[cqual] = via
                        frontier.append((cqual, via))
        out: list[Finding] = []
        for qual, via in sorted(reachable.items()):
            for ctx, node, hazard in self._hazards.get(qual, []):
                lineno = getattr(node, "lineno", 1)
                if ctx.allowed(self.name, lineno):
                    continue
                out.append(ctx.finding(
                    self.name, node,
                    f"{hazard} [signal-reachable via {via}]"))
        return out


class MetricRegistryRule(Rule):
    """PR 11's FLEET_* dedup review fix, generalized: every name passed
    to ``counter_add``/``gauge_set``/``observe`` must be a constant
    defined in ``utils/metrics.py`` (one spelling per series — raw
    literals are where the `makisu_fleet_route_total` /
    `makisu_fleet_routes_total` drift came from), and user-influenced
    ``tenant`` labels must route through a cardinality-capping helper
    so a hostile tenant mix cannot explode the process registry."""

    name = "metric-registry"
    description = ("metric names must be utils/metrics.py constants; "
                   "tenant-like labels must be cardinality-capped")

    _WRITES = {"counter_add", "gauge_set", "observe", "observe_batch"}
    _CAP_HELPERS = ("tenant_label", "cap_label")

    def __init__(self) -> None:
        self._constants: set[str] = set(self._module_constants())
        self._pending: list[tuple[FileContext, ast.Call, str]] = []

    @staticmethod
    def _module_constants() -> set[str]:
        """The registry: every ALL-CAPS string constant utils/metrics.py
        defines, read from the installed module so single-file scans
        (tests, editors) see the same registry a repo scan does."""
        try:
            from makisu_tpu.utils import metrics
        except Exception:  # pragma: no cover - broken tree mid-refactor
            return set()
        return {attr for attr in dir(metrics)
                if attr.isupper()
                and isinstance(getattr(metrics, attr), str)}

    def collect(self, ctx: FileContext) -> list[Finding]:
        if _file_is(ctx, "utils/metrics.py"):
            # The registry itself: constants live here, and its helpers
            # take the name as a parameter.
            for node in ast.walk(ctx.tree):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id.isupper()
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    self._constants.add(node.targets[0].id)
            return []
        # Module-level aliases of registry constants
        # (``PEER_CHUNK_HITS = metrics.FLEET_PEER_CHUNK_HITS``) resolve
        # one hop before the check.
        aliases: dict[str, str] = {}
        for node in ctx.tree.body if isinstance(ctx.tree, ast.Module) \
                else []:
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id.isupper()):
                target = self._const_name(node.value)
                if target and target.isupper():
                    aliases[node.targets[0].id] = target
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if last_attr(node) not in self._WRITES:
                continue
            name_expr = (node.args[0] if node.args
                         else keyword_arg(node, "name"))
            if name_expr is not None:
                verdict = self._check_name(name_expr, aliases)
                if verdict == "literal":
                    out.append(ctx.finding(
                        self.name, node,
                        f"raw metric name literal "
                        f"{expr_text(name_expr)}; define a constant "
                        f"in utils/metrics.py and reference it"))
                elif verdict == "computed":
                    out.append(ctx.finding(
                        self.name, node,
                        "computed metric name; metric names must be "
                        "utils/metrics.py constants"))
                elif verdict == "unknown-constant":
                    const = self._const_name(name_expr)
                    self._pending.append((ctx, node,
                                          aliases.get(const, const)))
            tenant = keyword_arg(node, "tenant")
            if tenant is not None and not self._capped(tenant):
                out.append(ctx.finding(
                    self.name, node,
                    "user-influenced tenant label is not routed "
                    "through a cardinality-capping helper "
                    "(e.g. scheduler.tenant_label)"))
        return out

    @staticmethod
    def _const_name(expr: ast.expr) -> str:
        if isinstance(expr, ast.Attribute):
            return expr.attr
        if isinstance(expr, ast.Name):
            return expr.id
        return ""

    def _check_name(self, expr: ast.expr,
                    aliases: dict[str, str]) -> str:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return "literal"
        if isinstance(expr, (ast.JoinedStr, ast.BinOp)):
            return "computed"
        name = self._const_name(expr)
        if name and name.isupper():
            name = aliases.get(name, name)
            # Defer: utils/metrics.py may not have been scanned yet.
            return ("ok" if name in self._constants
                    else "unknown-constant")
        # A lowercase variable: a pass-through helper's parameter —
        # checked at ITS call sites, not here.
        return "ok"

    def _capped(self, expr: ast.expr) -> bool:
        if isinstance(expr, ast.Constant):
            return True  # a static label is not user-influenced
        if isinstance(expr, ast.Call):
            return any(h in (last_attr(expr) or "")
                       for h in self._CAP_HELPERS)
        return False

    def finalize(self) -> list[Finding]:
        out: list[Finding] = []
        for ctx, node, const in self._pending:
            if const in self._constants:
                continue
            lineno = getattr(node, "lineno", 1)
            if ctx.allowed(self.name, lineno):
                continue
            out.append(ctx.finding(
                self.name, node,
                f"metric name constant {const} is not defined in "
                f"utils/metrics.py"))
        return out


class AtomicWriteRule(Rule):
    """PR 10's statcache fix: a ``json.dump`` straight onto a state
    file leaves a truncated half-JSON behind when the process dies
    mid-write (SIGTERM, OOM, power cut) — the next build then fails on
    the torn file or silently starts cold. Durable JSON goes through
    ``fileio.write_json_atomic`` (unique temp + fsync + rename)."""

    name = "atomic-write"
    description = ("json.dump to durable files must use "
                   "fileio.write_json_atomic")

    # The sanctioned implementations of the atomic write itself.
    _EXEMPT = ("utils/fileio.py", "utils/metrics.py")

    def collect(self, ctx: FileContext) -> list[Finding]:
        if _file_is(ctx, *self._EXEMPT):
            return []
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and last_attr(node) == "dump"
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in ("json", "json_mod")):
                out.append(ctx.finding(
                    self.name, node,
                    "direct json.dump to a file; a crash mid-write "
                    "truncates durable state — use "
                    "fileio.write_json_atomic"))
        return out


class SilentSwallowRule(Rule):
    """The sink/thread review staple: a broad ``except Exception``
    whose body neither re-raises nor makes ANY call (no log line, no
    dropped-counter bump) erases the failure completely — the bug
    class behind every "the build silently did nothing" report. Narrow
    exception types are fine; broad catches must leave a trace."""

    name = "silent-swallow"
    description = ("broad except blocks must log, count, or re-raise "
                   "— never swallow silently")

    _BROAD = {"Exception", "BaseException"}

    def collect(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if self._leaves_trace(node):
                continue
            out.append(ctx.finding(
                self.name, node,
                "broad except swallows the failure without logging, "
                "counting, or re-raising; narrow the type, log it, or "
                "bump a dropped-counter"))
        return out

    def _is_broad(self, type_node: ast.expr | None) -> bool:
        if type_node is None:
            return True  # bare except
        if isinstance(type_node, ast.Name):
            return type_node.id in self._BROAD
        if isinstance(type_node, ast.Tuple):
            return any(self._is_broad(e) for e in type_node.elts)
        return False

    @staticmethod
    def _leaves_trace(handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, (ast.Raise, ast.Call)):
                return True
        return False


class UnboundedIORule(Rule):
    """The timeout discipline the transport layer already follows,
    enforced: a socket or HTTP connection constructed without a
    timeout turns a wedged peer into a wedged build — the exact
    failure mode the stall watchdog exists to catch, except the
    watchdog can only dump it, not prevent it."""

    name = "unbounded-io"
    description = ("socket/HTTPConnection construction must carry a "
                   "timeout")

    def collect(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            tail = last_attr(node)
            message = None
            if name.endswith("socket.create_connection"):
                if not self._has_timeout(node, min_positional=2):
                    message = ("socket.create_connection without a "
                               "timeout")
            elif (tail.endswith("HTTPConnection")
                  or tail.endswith("HTTPSConnection")):
                # Only this repo's Unix-socket subclasses take
                # (path, timeout, ...) positionally; for everything
                # else — most importantly stdlib
                # http.client.HTTPConnection(host, port) — two
                # positional args are NOT a timeout.
                min_pos = 2 if tail.startswith("_Unix") else 99
                if not self._has_timeout(node, min_positional=min_pos):
                    message = (f"{tail} constructed without a timeout")
            elif tail == "urlopen":
                if not self._has_timeout(node, min_positional=3):
                    message = "urllib.request.urlopen without a timeout"
            if message:
                out.append(ctx.finding(
                    self.name, node,
                    f"{message}; a wedged peer becomes a wedged "
                    f"build — pass timeout="))
        return out

    @staticmethod
    def _has_timeout(node: ast.Call, min_positional: int) -> bool:
        if keyword_arg(node, "timeout") is not None:
            return True
        return len(node.args) >= min_positional


ALL_RULES = (CtxPropagationRule, SignalSafetyRule, MetricRegistryRule,
             AtomicWriteRule, SilentSwallowRule, UnboundedIORule)


def default_rules() -> list[Rule]:
    """Fresh rule instances (whole-program rules carry state; a run
    must never reuse another run's)."""
    return [cls() for cls in ALL_RULES]
