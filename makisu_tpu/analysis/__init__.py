"""Repo-invariant static analysis (`makisu-tpu check`).

The rule engine (:mod:`engine`) + six rules distilled from shipped
bugs (:mod:`rules`), with per-line ``# check: allow(<rule>)`` pragmas
and a committed ``baseline.json`` so pre-existing findings never block
while new ones fail CI. See docs/ANALYSIS.md for the catalog.
"""

from __future__ import annotations

import os

from makisu_tpu.analysis.engine import (BASELINE_SCHEMA, Finding,
                                        FileContext, Rule,
                                        apply_baseline, load_baseline,
                                        run_check, write_baseline)
from makisu_tpu.analysis.rules import ALL_RULES, default_rules

__all__ = [
    "BASELINE_SCHEMA", "Finding", "FileContext", "Rule", "ALL_RULES",
    "apply_baseline", "default_baseline_path", "default_rules",
    "default_scan_paths", "load_baseline", "run_check",
    "write_baseline", "repo_root",
]


def repo_root() -> str:
    """The checkout root (parent of the makisu_tpu package) — what
    finding paths and the committed baseline are relative to."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_scan_paths() -> list[str]:
    """What `makisu-tpu check` scans by default: the product package.
    Tests/fixtures deliberately excluded — they contain intentional
    violations."""
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")
