"""Rule engine for `makisu-tpu check`: repo-invariant static analysis.

Four consecutive review rounds (PRs 2, 4, 10, 11) each re-caught the
same invariant classes by hand — contextvars must ride into thread
pools, signal-context code must never block on a lock, durable state
must be written atomically, metric names must come from the
``utils/metrics.py`` registry. This engine mechanizes those reviews:

- :class:`Rule` — an AST-visitor rule. ``collect(ctx)`` runs once per
  file and may return findings immediately; whole-program rules (the
  signal-safety call graph) accumulate in ``collect`` and emit from
  ``finalize()`` once every file has been seen.
- Pragmas: ``# check: allow(<rule>[, <rule>...])`` on the finding line
  or the line directly above suppresses that rule there — the reviewed,
  in-source equivalent of a lint ignore, greppable by rule name.
- Baseline: a committed JSON file of pre-existing findings so the gate
  fails only on NEW violations. Findings are keyed by
  ``(rule, path, stripped source line)`` with a count — stable across
  unrelated edits that shift line numbers, invalidated exactly when
  the flagged line itself changes (which IS a new finding to review).

The engine is stdlib-only (ast + json) and imports nothing from the
build tree, so `check` runs in CI before anything else is importable.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Any, Iterable

BASELINE_SCHEMA = "makisu-tpu.analysis-baseline.v1"

_PRAGMA_RE = re.compile(r"#\s*check:\s*allow\(([^)]*)\)")


class Finding:
    """One rule violation at a source location."""

    __slots__ = ("rule", "path", "line", "col", "message", "snippet")

    def __init__(self, rule: str, path: str, line: int, col: int,
                 message: str, snippet: str) -> None:
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.snippet = snippet

    @property
    def fingerprint(self) -> tuple[str, str, str]:
        # Line numbers deliberately excluded: the baseline must survive
        # unrelated edits above the flagged line. The stripped line text
        # pins the finding to its code — edit the line, and it is a new
        # finding again.
        return (self.rule, self.path, self.snippet)

    def to_dict(self) -> dict[str, Any]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "snippet": self.snippet}

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: [{self.rule}] "
                f"{self.message}\n    {self.snippet}")

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Finding({self.rule}, {self.path}:{self.line})"


class FileContext:
    """One parsed source file handed to every rule's ``collect``."""

    def __init__(self, path: str, abspath: str, source: str,
                 tree: ast.AST) -> None:
        self.path = path          # repo-relative, forward slashes
        self.abspath = abspath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._allows: dict[int, set[str]] = {}
        for lineno, text in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self._allows[lineno] = rules

    def allowed(self, rule: str, lineno: int) -> bool:
        """Pragma check: the finding line itself or the line above."""
        for at in (lineno, lineno - 1):
            rules = self._allows.get(at)
            if rules and (rule in rules or "*" in rules):
                return True
        return False

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule, self.path, line, col, message,
                       self.line_text(line))


class Rule:
    """Base rule. Subclasses set ``name``/``description`` and override
    ``collect`` (and ``finalize`` for whole-program rules)."""

    name = "rule"
    description = ""

    def collect(self, ctx: FileContext) -> list[Finding]:
        raise NotImplementedError

    def finalize(self) -> list[Finding]:
        return []


def call_name(node: ast.Call) -> str:
    """Dotted name of a call's callee, best effort: ``threading.Thread``,
    ``metrics.counter_add``, ``x.y.submit``; "" for computed callees."""
    parts: list[str] = []
    cur: ast.AST = node.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif parts:
        # Receiver is an expression (a call, a subscript): keep the
        # attribute path, mark the base as opaque.
        parts.append("<expr>")
    else:
        return ""
    return ".".join(reversed(parts))


def last_attr(node: ast.Call) -> str:
    """The final attribute/name of a call's callee (``submit`` for
    ``a.b.submit(...)``)."""
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def keyword_arg(node: ast.Call, name: str) -> ast.expr | None:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    # Degrading to "" is the contract: rules treat an unrenderable
    # expression as unmatchable.  # check: allow(silent-swallow)
    except Exception:  # pragma: no cover - malformed synthetic nodes
        return ""


# -- file discovery ---------------------------------------------------------


_SKIP_DIRS = {"__pycache__", ".git", ".hypothesis", "node_modules"}


def iter_py_files(paths: Iterable[str]) -> list[str]:
    out: list[str] = []
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                out.append(os.path.abspath(path))
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.abspath(
                        os.path.join(dirpath, fn)))
    return sorted(set(out))


def run_check(paths: Iterable[str], rules: Iterable[Rule],
              root: str | None = None) -> list[Finding]:
    """Run every rule over every ``.py`` file under ``paths``. Finding
    paths are rendered relative to ``root`` (default: the common parent
    of the scanned paths) so baselines are repo-relocatable."""
    rules = list(rules)
    paths = list(paths)
    missing = [p for p in paths if not os.path.exists(p)]
    files = iter_py_files(p for p in paths if os.path.exists(p))
    if root is None:
        root = (os.path.commonpath([os.path.dirname(f) for f in files])
                if files else os.getcwd())
    root = os.path.abspath(root)
    findings: list[Finding] = []
    for path in missing:
        # A typo'd path must fail the gate, not scan zero files and
        # report a clean pass forever.
        findings.append(Finding(
            "parse-error", _relpath(os.path.abspath(path), root), 1, 0,
            "scan path does not exist", ""))
    for path in paths:
        # Same fail-loud contract for an explicit file argument that
        # exists but is not Python: silently scanning nothing looks
        # identical to a clean pass.
        if os.path.isfile(path) and not path.endswith(".py"):
            findings.append(Finding(
                "parse-error", _relpath(os.path.abspath(path), root),
                1, 0, "explicit scan path is not a .py file", ""))
    for abspath in files:
        try:
            with open(abspath, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=abspath)
        except (OSError, SyntaxError, ValueError) as e:
            rel = _relpath(abspath, root)
            findings.append(Finding(
                "parse-error", rel, 1, 0,
                f"file could not be analyzed: {e}", ""))
            continue
        ctx = FileContext(_relpath(abspath, root), abspath, source, tree)
        for rule in rules:
            for finding in rule.collect(ctx):
                if not ctx.allowed(finding.rule, finding.line):
                    findings.append(finding)
    # Whole-program rules emit after the full tree has been seen; their
    # findings carry their own FileContext pragma decision (the engine
    # cannot re-check here without re-reading files, so finalize-phase
    # rules filter pragmas themselves via the contexts they retained).
    for rule in rules:
        findings.extend(rule.finalize())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _relpath(abspath: str, root: str) -> str:
    try:
        rel = os.path.relpath(abspath, root)
    except ValueError:  # pragma: no cover - cross-drive (windows)
        rel = abspath
    if rel.startswith(".."):
        rel = abspath
    return rel.replace(os.sep, "/")


# -- baseline ---------------------------------------------------------------


def load_baseline(path: str) -> dict[tuple[str, str, str], int]:
    """Baseline file → fingerprint → allowed count. Missing file is an
    empty baseline (everything surfaces)."""
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except OSError:
        return {}
    if raw.get("schema") != BASELINE_SCHEMA:
        raise ValueError(
            f"{path}: not an analysis baseline "
            f"(schema {raw.get('schema')!r}, want {BASELINE_SCHEMA!r})")
    out: dict[tuple[str, str, str], int] = {}
    for entry in raw.get("findings", []):
        key = (entry["rule"], entry["path"], entry["snippet"])
        out[key] = out.get(key, 0) + int(entry.get("count", 1))
    return out


def apply_baseline(findings: list[Finding],
                   baseline: dict[tuple[str, str, str], int]
                   ) -> tuple[list[Finding], int]:
    """Split findings into (new, suppressed_count). The first N
    occurrences of a baselined fingerprint are suppressed; occurrences
    beyond the recorded count surface as new."""
    remaining = dict(baseline)
    new: list[Finding] = []
    suppressed = 0
    for f in findings:
        left = remaining.get(f.fingerprint, 0)
        if left > 0:
            remaining[f.fingerprint] = left - 1
            suppressed += 1
        else:
            new.append(f)
    return new, suppressed


def write_baseline(path: str, findings: list[Finding]) -> None:
    """Serialize ALL current findings as the new baseline (sorted and
    count-folded for stable diffs). Written atomically the same way the
    telemetry reports are — a killed `--update-baseline` must not leave
    a torn gate file."""
    counts: dict[tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
    payload = {
        "schema": BASELINE_SCHEMA,
        "comment": "Pre-existing `makisu-tpu check` findings, keyed by "
                   "(rule, path, source line). New findings fail the "
                   "gate; regenerate with "
                   "`makisu-tpu check --update-baseline` and review "
                   "the diff.",
        "findings": [
            {"rule": rule, "path": fpath, "snippet": snippet,
             "count": count}
            for (rule, fpath, snippet), count in sorted(counts.items())
        ],
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            # This IS an atomic write (unique temp + os.replace);
            # fileio would be a circular import from the one module
            # that must import nothing from the build tree.
            # check: allow(atomic-write)
            json.dump(payload, f, indent=1, sort_keys=False)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
