"""BuildContext: shared state threaded through steps of one stage.

Reference: lib/context/build_context.go:35-88. Adds one field the reference
lacks: the ``hasher`` seam (chunker.Hasher) every committed layer streams
through — the TPU/CPU backend selection point.
"""

from __future__ import annotations

import base64
import os

from makisu_tpu.chunker import CPUHasher, Hasher
from makisu_tpu.snapshot import MemFS
from makisu_tpu.storage import ImageStore
from makisu_tpu.utils import pathutils

_STAGES_DIR = "stages"


class BuildContext:
    def __init__(self, root_dir: str, context_dir: str,
                 image_store: ImageStore,
                 hasher: Hasher | None = None,
                 blacklist: list[str] | None = None,
                 sync_wait: float | None = None,
                 gzip_backend_id: str | None = None) -> None:
        self.root_dir = root_dir
        self.context_dir = context_dir
        self.image_store = image_store
        self.stage_vars: dict[str, str] = {}
        self.copy_ops = []
        self.must_scan = False
        # Per-build process environment for RUN steps. ARG/ENV exports
        # land here, never in os.environ — concurrent builds in one
        # worker process must not see each other's variables. Each stage
        # starts from the snapshot taken at build start (the reference
        # restores os.environ between stages, build_plan.go:197-204).
        self._base_env: dict[str, str] = dict(os.environ)
        self.exec_env: dict[str, str] = dict(self._base_env)
        # Per-build compression identity (tario.make_backend_id); None
        # falls back to the process default. Lives here, not in tario's
        # globals, so concurrent builds with different flags don't race.
        self.gzip_backend_id = gzip_backend_id
        self.hasher = hasher or CPUHasher()
        self.stages_dir = os.path.join(image_store.sandbox_dir, _STAGES_DIR)
        os.makedirs(self.stages_dir, exist_ok=True)
        if blacklist is None:
            blacklist = list(pathutils.DEFAULT_BLACKLIST)
        # Without the build-internal dirs: copy-op sources legitimately
        # live in the context dir, so steps extend this base themselves.
        self.base_blacklist = list(blacklist)
        self.blacklist = blacklist + [context_dir, image_store.root]
        kwargs = {} if sync_wait is None else {"sync_wait": sync_wait}
        self.memfs = MemFS(root_dir, self.blacklist, **kwargs)
        # .dockerignore (capability beyond the reference): the excluded
        # path set is computed lazily on first context COPY/ADD and
        # cached for the build.
        self._ignore_excluded: list[str] | None = None
        self._ignore_prefixes = None  # PrefixSet over _ignore_excluded
        # Stat-keyed content-ID cache (utils/statcache.py): warm builds
        # skip re-reading context files whose (size, mtime, ctime,
        # inode) is unchanged. Lives in the storage dir beside the KV
        # cache; BuildPlan.execute saves it.
        from makisu_tpu.utils.statcache import ContentIDCache
        self.content_ids = ContentIDCache(
            os.path.join(image_store.root, "content_id_cache.json"),
            namespace=os.path.abspath(context_dir))
        # Resident build session (worker/session.py), armed by
        # session.begin_build for warm rebuilds: dirty_paths is the set
        # of context paths that changed since the last build of this
        # context, dirty_exact says whether that set provably covers
        # every change (only then may scans be skipped).
        self.session = None
        self.dirty_paths: frozenset[str] = frozenset()
        self.dirty_exact = False

    def source_unchanged(self, path: str) -> bool:
        """True when the resident session PROVES nothing under ``path``
        changed since the last build: the dirty set is exact and no
        dirty path is ``path``, below it, or an ANCESTOR of it (a
        renamed/moved parent dirties every source inside it even when
        the watcher only evented the parent itself). Gate for every
        scan-memo shortcut — when this is False, the full walk runs
        (cold-path semantics, cold-path results)."""
        if not self.dirty_exact:
            return False
        prefix = path.rstrip("/") + "/"
        for dirty in self.dirty_paths:
            if (dirty == path or dirty.startswith(prefix)
                    or prefix.startswith(dirty.rstrip("/") + "/")):
                return False
        return True

    def context_excluded_paths(self) -> list[str]:
        """Absolute context paths excluded by .dockerignore (empty when
        the file is absent)."""
        if self._ignore_excluded is None:
            from makisu_tpu.utils.dockerignore import DockerIgnore, PrefixSet
            ignore = DockerIgnore.load(self.context_dir)
            self._ignore_excluded = (
                ignore.excluded_paths(self.context_dir) if ignore else [])
            self._ignore_prefixes = PrefixSet(self._ignore_excluded)
            if self._ignore_excluded:
                from makisu_tpu.utils import logging as log
                log.info(".dockerignore excludes %d context paths",
                         len(self._ignore_excluded))
        return self._ignore_excluded

    def context_path_ignored(self, path: str) -> bool:
        """O(log n) .dockerignore probe (the checksum/copy walks call
        this once per context path)."""
        self.context_excluded_paths()
        return self._ignore_prefixes.covers(path)

    def copy_from_root(self, alias: str) -> str:
        """Sandbox dir holding stage ``alias``'s checkpointed files for
        COPY --from (reference: CopyFromRoot build_context.go:83)."""
        dirname = base64.urlsafe_b64encode(alias.encode()).decode()
        return os.path.join(self.stages_dir, dirname)

    def new_stage_context(self) -> "BuildContext":
        """Fresh per-stage context sharing the store and root (the MemFS
        restarts empty each stage)."""
        ctx = BuildContext.__new__(BuildContext)
        ctx.root_dir = self.root_dir
        ctx.context_dir = self.context_dir
        ctx.image_store = self.image_store
        ctx.stage_vars = {}
        ctx.copy_ops = []
        ctx.must_scan = False
        ctx._base_env = self._base_env
        ctx.exec_env = dict(self._base_env)
        ctx.gzip_backend_id = self.gzip_backend_id
        ctx.hasher = self.hasher
        ctx.stages_dir = self.stages_dir
        ctx.base_blacklist = self.base_blacklist
        ctx.blacklist = self.blacklist
        ctx.memfs = MemFS(self.root_dir, self.blacklist,
                          sync_wait=self.memfs.sync_wait)
        ctx._ignore_excluded = self._ignore_excluded
        ctx._ignore_prefixes = self._ignore_prefixes
        # SHARED, not fresh: stages hash the same context files, and
        # the plan saves the base context's cache once at the end.
        ctx.content_ids = self.content_ids
        # Session state is shared too: every stage scans the same
        # context tree under the same dirty set.
        ctx.session = self.session
        ctx.dirty_paths = self.dirty_paths
        ctx.dirty_exact = self.dirty_exact
        return ctx
