"""Shared hash service: many concurrent builds, one accelerator.

A build farm node (worker mode, BASELINE config 5: 64 concurrent jobs
sharing a chip/mesh) must not let each build dispatch its own half-empty
lane batches. The service multiplexes chunk-hash requests from every
in-process ChunkSession into full fixed-shape lane batches behind a
single dispatcher thread: callers submit chunk bytes and get a Future;
the dispatcher packs whatever is pending (up to the bucket's lane count,
with a short linger for stragglers), dispatches one program, and
resolves futures on readback.

Effects: device programs stay the two compiled bucket shapes, batches
run full under concurrency, and per-build latency is bounded by the
linger (default 2ms) instead of other builds' progress.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

from makisu_tpu.ops import sha256
from makisu_tpu.chunker.cdc import _BUCKETS
from makisu_tpu.utils import metrics

# Batch-size histogram buckets: lane-fill powers of two up to the
# largest bucket's lane count.
_FILL_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                 512.0)

# Occupancy histogram buckets: lanes filled ÷ lane capacity per
# dispatched program. The fleet-batching signal (ROADMAP item 1): a
# worker whose occupancy sits near 1.0 is amortizing device programs
# across builds; near 1/lanes it is dispatching half-empty batches and
# more concurrency (or a longer linger) would pay.
_OCCUPANCY_BUCKETS = (0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0)

class HashService:
    """Cross-build chunk-hash batcher. Thread-safe; one per process."""

    # Backpressure: per-bucket queue depth caps pending chunk BYTES at
    # ~2 full batches; faster producers block in submit() instead of
    # accumulating host memory without bound.
    QUEUE_DEPTH_BATCHES = 2

    def __init__(self, linger_seconds: float | None = None) -> None:
        if linger_seconds is None:
            # --hash-linger-ms / MAKISU_TPU_HASH_LINGER_MS (2ms
            # default); utils.concurrency owns the knob so the CLI can
            # read it without importing the device stack.
            from makisu_tpu.utils import concurrency
            linger_seconds = concurrency.hash_linger_ms() / 1000.0
        self.linger = linger_seconds
        self._queues: list[queue.Queue] = [
            queue.Queue(maxsize=lanes * self.QUEUE_DEPTH_BATCHES)
            for _, lanes in _BUCKETS]
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._dispatch_loop, args=(i,),
                             daemon=True, name=f"hashsvc-{cap}")
            for i, (cap, _) in enumerate(_BUCKETS)
        ]
        self.batches = 0  # dispatched program count (observability)
        # Batches that mixed chunks from >1 submitting session — direct
        # evidence that concurrent builds share device programs.
        self.cross_build_batches = 0
        for t in self._threads:
            t.start()

    def submit(self, data: bytes, owner=None) -> "Future[bytes]":
        """Hash one chunk; resolves to the 32-byte sha256 digest.
        ``owner`` identifies the submitting session (observability)."""
        fut: Future = Future()
        for i, (cap, _) in enumerate(_BUCKETS):
            if len(data) <= cap - 64:
                self._queues[i].put((data, fut, owner))
                return fut
        raise ValueError(f"chunk of {len(data)} bytes exceeds every bucket")

    def _dispatch_loop(self, bucket: int) -> None:
        cap, lanes = _BUCKETS[bucket]
        q = self._queues[bucket]
        while not self._stop.is_set():
            try:
                first = q.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            # Linger briefly to fill the batch from concurrent builds.
            end = self.linger
            t0 = time.monotonic()
            while len(batch) < lanes:
                remaining = end - (time.monotonic() - t0)
                if remaining <= 0:
                    break
                try:
                    batch.append(q.get(timeout=remaining))
                except queue.Empty:
                    break
            metrics.observe("makisu_hash_batch_linger_seconds",
                            time.monotonic() - t0, bucket=cap)
            metrics.gauge_set("makisu_hash_queue_depth", q.qsize(),
                              bucket=cap)
            self._run_batch(cap, lanes, batch)

    def _run_batch(self, cap: int, lanes: int, batch) -> None:
        data = np.zeros((lanes, cap), dtype=np.uint8)
        lengths = np.zeros(lanes, dtype=np.int32)
        for i, (chunk, _, _) in enumerate(batch):
            data[i, :len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
            lengths[i] = len(chunk)
        t0 = time.monotonic()
        try:
            from makisu_tpu.ops import backend as _backend
            from makisu_tpu.ops import sha256_pallas
            words = _backend.sync_bounded(
                sha256_pallas.sha256_lanes_auto(data, lengths),
                "shared-service digest readback")
        except BaseException as e:  # noqa: BLE001
            metrics.counter_add("makisu_hash_batch_failures_total",
                                bucket=cap)
            for _, fut, _ in batch:
                fut.set_exception(e)
            return
        self.batches += 1
        # Device execution telemetry: dispatch latency ring + compile
        # gauge + H2D/padding-waste bytes, per bucket (ops/backend.py
        # owns the shared accounting so the lane batcher's direct
        # route exports identical series).
        _backend.note_device_dispatch(cap, lanes, len(batch),
                                      int(lengths.sum()),
                                      time.monotonic() - t0)
        owners = {owner for _, _, owner in batch if owner is not None}
        if len(owners) > 1:
            self.cross_build_batches += 1
            metrics.counter_add("makisu_hash_cross_build_batches_total")
        # NOTE: the dispatcher thread runs outside any build's context,
        # so these land in the process-global registry only — correct:
        # a batch can mix several builds' chunks.
        metrics.counter_add("makisu_hash_batches_total", bucket=cap)
        metrics.counter_add("makisu_bytes_hashed_total",
                            int(lengths.sum()),
                            backend=sha256_pallas.last_route,
                            path="service")
        metrics.observe("makisu_hash_batch_seconds",
                        time.monotonic() - t0, bucket=cap)
        metrics.observe("makisu_hash_batch_fill", len(batch),
                        buckets=_FILL_BUCKETS, bucket=cap)
        metrics.observe("makisu_hash_batch_occupancy",
                        len(batch) / lanes,
                        buckets=_OCCUPANCY_BUCKETS, bucket=cap)
        for i, (_, fut, _) in enumerate(batch):
            fut.set_result(words[i].astype(">u4").tobytes())

    def close(self) -> None:
        """Stop dispatchers; fail any still-queued futures so no caller
        blocks forever in fut.result()."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        for q in self._queues:
            while True:
                try:
                    _, fut, _ = q.get_nowait()
                except queue.Empty:
                    break
                fut.set_exception(RuntimeError("hash service closed"))


_global_service: HashService | None = None
_global_lock = threading.Lock()


def shared_service() -> HashService:
    """Process-wide service (worker mode enables it for all builds)."""
    global _global_service
    with _global_lock:
        if _global_service is None:
            _global_service = HashService()
        return _global_service
