"""Streaming content-defined chunking + lane-parallel chunk hashing.

A ``ChunkSession`` consumes an arbitrarily long byte stream in fixed-size
blocks and produces content-defined chunks with SHA-256 fingerprints:

1. Each block ships to the accelerator once; ``ops.gear.gear_bitmap``
   returns a bit-packed candidate-boundary bitmap (3% readback). Blocks
   after the first carry a ``WINDOW``-byte halo from the previous block so
   per-position hashes are identical to one continuous stream.
2. A greedy host pass applies min/max chunk-size policy to the candidate
   positions (cheap: a few comparisons per candidate, not per byte).
3. Chunk bytes batch into fixed-shape lane buffers — bucketed capacities
   so XLA compiles one program per bucket, never per input — and hash in
   lock-step on the VPU (``ops.sha256.sha256_lanes``).

Everything dispatches asynchronously; device→host syncs happen only for
bitmap readback and at ``finish()``.

Failure discipline: chunk fingerprints are an OPTIMIZATION (they enable
chunk-granular cache dedup); the layer's registry identity comes from
the CPU digests. So a device failure mid-stream (backend died, tunnel
dropped, OOM) degrades the session — the layer commits with an empty
chunk list and whole-layer caching only — instead of failing the build.
Backend-init HANGS (a wedged tunnel blocks ``jax.devices()`` forever and
never raises) are caught the same way via a bounded, process-cached
probe at session construction (ops/backend.py) — observed live on a
v5e host whose tunnel wedged mid-session (2026-07).
``MAKISU_TPU_CHUNK_STRICT=1`` re-raises instead (tests/debugging).

This is the long-stream scaling design the reference lacks (its hashing is
a single sequential SHA-256 stream, lib/builder/step/common.go:35-67); see
SURVEY.md §5 "long-context" mapping.
"""

from __future__ import annotations

import contextvars
import time
import typing

import jax
import numpy as np

from makisu_tpu.ops import backend as _backend
from makisu_tpu.ops import gear, sha256
from makisu_tpu.utils import concurrency, metrics

BLOCK = 4 * 1024 * 1024  # bytes shipped to the device per gear dispatch

# Chunk bytes accumulated before one pooled SHA task dispatches. Sized
# for GIL economics, not just task overhead: a pooled task is ONE
# GIL-released native call (native.sha256_batch), and each task costs
# ~2 GIL acquisitions (entry + return) that can each wait a full
# 5ms switch interval behind the GIL-bound producer thread — so
# batches must be big enough that hashing time dwarfs handoff time.
SHA_BATCH_BYTES = 1024 * 1024

# makisu_chunk_size_bytes histogram ladder: powers of two around the
# 8KiB average / 64KiB max chunk policy (gear.DEFAULT_*).
CHUNK_SIZE_BUCKETS = (1024.0, 2048.0, 4096.0, 8192.0, 16384.0,
                      32768.0, 65536.0, 131072.0)

# Fingerprint observer: the chunk-dedup cache registers a callback per
# build (cache/chunks.attach_chunk_dedup) and CAS-existence lookups
# issue as fingerprints stream out of the hash stage, instead of as a
# serial stat storm after finish(). Context-scoped like the metrics
# registry so concurrent worker builds never observe each other's
# chunks. Observers must be thread-safe and non-raising: they are
# called from pool workers on the commit hot path.
_chunk_observer: "contextvars.ContextVar" = contextvars.ContextVar(
    "makisu_chunk_observer", default=None)


def set_chunk_observer(cb):
    """Bind a per-context fingerprint callback ``cb(hex_digest)``.
    Returns a token for :func:`reset_chunk_observer`."""
    return _chunk_observer.set(cb)


def reset_chunk_observer(token) -> None:
    _chunk_observer.reset(token)


def _native_cpu_route() -> bool:
    """Whether this process should chunk natively (runtime-dispatched
    C++ gear scan + batch SHA-256, makisu_tpu/native.py ISA ladder)
    instead of driving the JAX backend: only when that backend IS the
    CPU — same math, ~10x less overhead — never on a real accelerator.
    MAKISU_TPU_CHUNK_NATIVE=0 forces the XLA route (A/B, debugging)."""
    import os
    if os.environ.get("MAKISU_TPU_CHUNK_NATIVE", "1") != "1":
        return False
    try:
        if jax.default_backend() != "cpu":
            return False
    except Exception:  # noqa: BLE001 - backend init failure
        return False
    from makisu_tpu import native
    return native.gear_scan_available()


def _sha_batch_route() -> bool:
    """Whether the pooled multicore route can engage: it needs the
    native batch hasher (libgear.so gear_sha256_batch — one
    GIL-released call per ~MiB batch). Per-chunk hashlib on pool
    threads is NOT a substitute: at ~8KiB chunk sizes the GIL
    ping-pong against the producer thread scales negatively (measured
    0.6x on 2 cores), so without the symbol the session stays
    serial."""
    from makisu_tpu import native
    return native.sha_batch_available()

# Lane-buffer buckets: (capacity, lanes). Chunk avg is 8 KiB and max
# 64 KiB, so most chunks hash in the 16 KiB bucket; each bucket is one
# compiled XLA program reused forever.
_BUCKETS = ((16 * 1024, 512), (gear.DEFAULT_MAX_SIZE + 64, 128))


class Chunk(typing.NamedTuple):
    # NamedTuple, not a frozen dataclass: sessions create one per
    # ~8KiB chunk (~130k/GB), and tuple construction is ~5x cheaper
    # than frozen-dataclass __setattr__ — measurable on the native
    # serial route. Field access is unchanged.
    offset: int
    length: int
    digest: bytes  # 32-byte sha256

    @property
    def hex(self) -> str:
        return self.digest.hex()


class _LaneBatcher:
    """Accumulates chunks into one bucket's fixed [L, CAP] buffer and
    dispatches sha256_lanes when full."""

    def __init__(self, cap: int, lanes: int) -> None:
        self.cap = cap
        self.lanes = lanes
        self.data = np.zeros((lanes, cap), dtype=np.uint8)
        self.lengths = np.zeros(lanes, dtype=np.int32)
        self.meta: list[tuple[int, int]] = []  # (offset, length)
        self.pending: list[tuple[jax.Array, list[tuple[int, int]]]] = []

    def add(self, off: int, data: memoryview) -> None:
        i = len(self.meta)
        n = len(data)
        self.data[i, :n] = np.frombuffer(data, dtype=np.uint8)
        self.data[i, n:] = 0
        self.lengths[i] = n
        self.meta.append((off, n))
        if len(self.meta) == self.lanes:
            self.flush()

    def flush(self) -> None:
        if not self.meta:
            return
        from makisu_tpu.ops import sha256_pallas
        digests = sha256_pallas.sha256_lanes_auto(
            self.data, self.lengths)  # async dispatch
        metrics.counter_add("makisu_bytes_hashed_total",
                            sum(n for _, n in self.meta),
                            backend=sha256_pallas.last_route, path="cdc")
        self.pending.append((digests, self.meta))
        self.meta = []
        # Fresh buffers: the dispatched call may still be consuming the old
        # host arrays.
        self.data = np.zeros((self.lanes, self.cap), dtype=np.uint8)
        self.lengths = np.zeros(self.lanes, dtype=np.int32)

    def drain(self) -> list[Chunk]:
        self.flush()
        out: list[Chunk] = []
        for digests, meta in self.pending:
            t0 = time.monotonic()
            host = _backend.sync_bounded(
                digests, "lane digest readback")  # bounded sync point
            # Readback-wait per program (timed around the sync only:
            # dispatch was async at flush and the host kept scanning in
            # between — flush-to-drain wall time would charge that host
            # work to the device and poison the per-bucket digests the
            # shared HashService exports under the same names).
            _backend.note_device_dispatch(
                self.cap, self.lanes, len(meta),
                sum(n for _, n in meta), time.monotonic() - t0)
            for i, (off, n) in enumerate(meta):
                out.append(Chunk(off, n, host[i].astype(">u4").tobytes()))
        self.pending = []
        return out


class ChunkSession:
    """One layer stream → content-defined chunks with fingerprints."""

    # How many gear dispatches may be in flight before the host blocks on
    # the oldest bitmap. Depth 2 overlaps device scan + readback with the
    # caller producing the next block (tar writing / file IO).
    PIPELINE_DEPTH = 2

    def __init__(self, avg_bits: int = gear.DEFAULT_AVG_BITS,
                 min_size: int = gear.DEFAULT_MIN_SIZE,
                 max_size: int = gear.DEFAULT_MAX_SIZE,
                 block: int = BLOCK, service=None,
                 workers: int | None = None) -> None:
        if block % 32:
            raise ValueError("block size must be a multiple of 32")
        # Optional chunker.service.HashService: concurrent builds in one
        # process share full device batches instead of dispatching their
        # own (worker mode / build farms).
        self.service = service
        self._service_pending: list[tuple[int, int, object]] = []
        self.avg_bits = avg_bits
        self.min_size = min_size
        self.max_size = max_size
        self.block = block
        self._staging = bytearray()   # bytes not yet gear-scanned
        self._tail = bytearray()      # scanned bytes after the last cut
        self._tail_offset = 0         # stream offset of _tail[0]
        self._scanned = 0             # stream bytes gear-dispatched so far
        self._halo = b""              # last WINDOW bytes of previous block
        self._prev_cut = 0            # stream offset of the last cut
        self._inflight: list[tuple] = []  # dispatched, unprocessed blocks
        self._batchers = [_LaneBatcher(cap, lanes)
                          for cap, lanes in _BUCKETS]
        self._chunks: list[Chunk] = []
        # Batched-route state, defaulted before the backend probe below
        # (whose _degrade clears them). Pending chunks are (offset,
        # length) records tiling the tail's prefix [_tail_offset,
        # _prev_cut); the flush consumes that prefix in one slice and
        # one GIL-released native call.
        self._sha_meta: list[tuple[int, int]] = []  # (offset, length)
        self._sha_pending: list = []  # ordered (meta, Future->digests)
        self._degraded: str | None = None  # failure summary once degraded
        # Hang guard: a wedged TPU tunnel makes the first dispatch block
        # forever in backend init, which no exception handler can catch.
        # Probe (bounded, cached process-wide) before touching the
        # device; on failure this layer degrades exactly like a
        # mid-stream device error would.
        err = _backend.backend_ready()
        if err is not None:
            self._degrade("backend init", RuntimeError(err))
        # CPU hosts (build boxes with no accelerator) take the native
        # route: the runtime-dispatched C++ gear scan (AVX2 / striped /
        # scalar) + batch SHA-256 (SHA-NI / EVP / scalar), bit-identical
        # to the device formulation and ~10x driving XLA's CPU backend
        # through the vector form. The service path (cross-build device
        # batching) and non-cpu backends keep the device route.
        self._native = (self._degraded is None and service is None
                        and _native_cpu_route())
        # The gear table is deterministic by contract; one copy per
        # session, not one 256-iteration rebuild per 4MiB block.
        self._table = gear.gear_table() if self._native else None
        self._observer = _chunk_observer.get()
        # Bytes hashed on the native route, accumulated locally and
        # flushed once at finish(): a per-chunk counter_add (lock +
        # label sort, ×2 registries) measured ~13% of the whole native
        # session.
        self._native_hashed = 0
        # Multicore native route (the tentpole): gear block scans and
        # chunk SHA-256 run on the shared commit pool, with results
        # consumed in stream order so boundaries, digests, and chunk
        # ordering are byte-identical to the serial route. workers=1
        # is exactly the serial pipeline.
        self._workers = 1
        self._depth = self.PIPELINE_DEPTH
        self._pool = None
        self._sha_slots = None
        # Serial native route ALSO batches chunk SHA when the native
        # batch hasher exists: one GIL-released call per ~MiB batch
        # (SHA-NI multi-buffer when the CPU has it) instead of ~128
        # per-chunk hashlib round trips — same digests, same order.
        self._sha_sync = False
        if self._native:
            self._workers = (concurrency.hash_workers()
                             if workers is None else max(1, workers))
            if self._workers > 1 and _sha_batch_route():
                import threading
                self._pool = concurrency.hash_pool()
                # Scan deep enough that every worker can hold a block.
                self._depth = max(self.PIPELINE_DEPTH, self._workers)
                # Backpressure AND concurrency bound: at most `workers`
                # SHA batches in flight, so one session never runs more
                # simultaneous tasks than its configured parallelism on
                # the shared pool (oversubscription measured as a 3x
                # LOSS: 8 tasks + the producer thrashing 2 cores), and
                # resident batch bytes stay ≤ workers × SHA_BATCH_BYTES.
                self._sha_slots = threading.BoundedSemaphore(
                    self._workers)
                self._sha_depth = 0
                self._sha_depth_lock = threading.Lock()
            if self._pool is None:
                self._sha_sync = _sha_batch_route()

    # -- failure discipline ----------------------------------------------

    def _degrade(self, stage: str, exc: Exception) -> None:
        """Device failure: drop chunk tracking for this layer and let
        the build continue (whole-layer caching only). Never corrupts —
        a degraded layer simply has no fingerprints, and the regular
        chunk-dedup tests would fail if this path ever triggered on a
        healthy device."""
        import os

        from makisu_tpu.utils import logging as log
        if os.environ.get("MAKISU_TPU_CHUNK_STRICT") == "1":
            raise exc
        log.warning(
            "chunk fingerprinting disabled for this layer (%s: %s); "
            "build continues with whole-layer caching only", stage, exc)
        # Summary string, NOT the exception: its traceback would pin
        # the failing frames (4MiB blocks, numpy buffers) that the
        # clears below exist to release.
        self._degraded = f"{stage}: {exc}"
        self._staging.clear()
        self._tail.clear()
        self._inflight = []
        self._chunks = []
        self._service_pending = []
        # Batched-route state: pending tasks complete harmlessly on the
        # shared pool (they release their own slots); just drop the
        # references so their buffers free.
        self._sha_meta = []
        self._sha_pending = []
        for b in self._batchers:
            b.meta = []
            b.pending = []

    # -- byte intake ------------------------------------------------------

    def update(self, data: bytes) -> None:
        if self._degraded is not None:
            return
        self._staging.extend(data)
        while len(self._staging) >= self.block:
            # The scan buffer is assembled ONCE with the halo prefix in
            # place (join accepts the staging memoryview directly): one
            # copy instead of three (bytearray slice → bytes() → the
            # old per-scan halo+blk concat) — a full stream pass saved
            # on every route.
            halo_len = len(self._halo)
            with memoryview(self._staging) as mv:
                hblk = b"".join((self._halo, mv[:self.block]))
            del self._staging[:self.block]
            try:
                # (the dispatch also drains the oldest in-flight block
                # when the pipeline is full, so readback errors can
                # surface here too — hence the broader stage label)
                self._dispatch_block(hblk, halo_len, self.block)
            except Exception as e:  # noqa: BLE001 - device plane
                self._degrade("gear pipeline", e)
                return

    def finish(self) -> list[Chunk]:
        if self._degraded is None and self._staging:
            live = len(self._staging)
            pad = (-live) % 32  # exactly the pre-halo-prefix padding
            halo_len = len(self._halo)
            with memoryview(self._staging) as mv:
                hblk = b"".join((self._halo, mv, b"\x00" * pad))
            try:
                self._dispatch_block(hblk, halo_len, live)
            except Exception as e:  # noqa: BLE001 - device plane
                self._degrade("gear pipeline", e)
            self._staging.clear()
        while self._degraded is None and self._inflight:
            try:
                self._process_block(self._inflight.pop(0))
            except Exception as e:  # noqa: BLE001 - device plane
                self._degrade("gear readback", e)
        # Final chunk: whatever follows the last cut. _take routes it
        # like any forced cut — straight to the batch record on the
        # batched routes (the tail may still hold pending batch bytes,
        # so it must NOT be cleared here), immediate emit elsewhere.
        if self._degraded is None:
            stream_end = self._tail_offset + len(self._tail)
            if stream_end > self._prev_cut:
                try:
                    self._take(stream_end)
                except Exception as e:  # noqa: BLE001 - device plane
                    self._degrade("lane dispatch", e)
        if self._degraded is None:
            try:
                if self._pool is not None or self._sha_sync:
                    self._flush_sha_batch()
                if self._pool is not None:
                    for meta, fut in self._sha_pending:
                        raw = fut.result().tobytes()
                        self._chunks.extend(
                            Chunk(off, n, raw[32 * i:32 * i + 32])
                            for i, (off, n) in enumerate(meta))
                    self._sha_pending = []
                for b in self._batchers:
                    self._chunks.extend(b.drain())
                _t = _backend.sync_timeout()
                svc_timeout = _t if _t > 0 else None
                for offset, length, fut in self._service_pending:
                    # Bounded like the direct readbacks: a dead service
                    # dispatcher must degrade the layer, not block it.
                    self._chunks.append(
                        Chunk(offset, length,
                              fut.result(timeout=svc_timeout)))
            except Exception as e:  # noqa: BLE001 - device plane
                self._degrade("lane hashing", e)
        if self._native_hashed:
            # One flush for the whole stream (a per-chunk counter_add
            # measured ~13% of the native session); degraded sessions
            # still record the bytes they DID hash.
            metrics.counter_add("makisu_bytes_hashed_total",
                                self._native_hashed,
                                backend="native", path="cdc")
            self._native_hashed = 0
        if self._pool is not None:
            # The session is drained: a long-lived worker's /metrics
            # must not keep showing the last submit-time backlog.
            metrics.stage_queue_depth("gear_scan", 0)
            metrics.stage_queue_depth("chunk_sha", 0)
        if self._degraded is not None:
            return []
        self._service_pending = []
        self._chunks.sort(key=lambda c: c.offset)
        if self._chunks:
            # One batched fold per stream (never per chunk): chunking
            # efficiency — are cuts landing near the 8KiB target, or
            # degenerating to min/max forced cuts? — visible in
            # /metrics without a ledger.
            metrics.observe_batch("makisu_chunk_size_bytes",
                                  [c.length for c in self._chunks],
                                  buckets=CHUNK_SIZE_BUCKETS)
        return self._chunks

    # -- internals --------------------------------------------------------

    def _dispatch_block(self, hblk: bytes, halo_len: int,
                        live: int) -> None:
        """Ship one block to the scan stage (device dispatch, or the
        commit pool on the multicore native route); process the oldest
        in-flight block when the pipeline is full.

        ``hblk`` arrives with the previous block's halo already in
        place (``hblk[:halo_len]``) and the live stream bytes at
        ``hblk[halo_len:halo_len + live]`` (anything after is zero
        padding on the final block) — assembled once by the caller, so
        no scan route re-concatenates the 4MiB buffer."""
        from makisu_tpu.ops import gear_pallas
        entry = None
        scan_backend = None  # executing backend when != entry[0]'s tag
        if self._native:
            if self._pool is not None:
                # Pooled scan: each block's candidates are a pure
                # function of (halo, block) — the same inputs the
                # synchronous scan sees — so blocks scan in parallel
                # across the pool while _process_block consumes results
                # in stream order. Boundaries are byte-identical.
                fut = concurrency.submit_ctx(
                    self._pool, self._scan_task, hblk, halo_len, live)
                entry = ("native", fut, halo_len, live, hblk,
                         self._scanned)
                metrics.stage_queue_depth("gear_scan",
                                          len(self._inflight) + 1)
            else:
                # Synchronous by design: the scan is faster than a
                # device round trip, so there is nothing to overlap.
                # The C++ scan returns candidate POSITIONS directly —
                # no bit array, no host-side nonzero rescan.
                entry = ("native",
                         self._scan_positions(hblk, halo_len, live),
                         halo_len, live, hblk, self._scanned)
        if entry is None:
            buf = np.frombuffer(hblk, dtype=np.uint8)
        if entry is None and gear_pallas.v2_enabled():
            # Opt-in natural-layout kernel (MAKISU_TPU_PALLAS_V2=1):
            # pure-reshape staging, full-buffer bitmap (XLA-contract
            # slicing) — see gear_pallas.py v2 block.
            try:
                need = ((len(buf) + gear_pallas.V2_TILE - 1)
                        // gear_pallas.V2_TILE) * gear_pallas.V2_TILE
                if need != len(buf):
                    qbuf = np.zeros(need, dtype=np.uint8)
                    qbuf[:len(buf)] = buf
                else:
                    qbuf = buf
                words = gear_pallas.gear_bitmap_flat2(
                    qbuf, self.avg_bits,
                    interpret=jax.default_backend() == "cpu")
                # entry[0] is the READBACK layout tag (v2 words decode
                # like XLA's), not the executing backend.
                entry = ("xla", words, halo_len, live, hblk,
                         self._scanned)
                scan_backend = "pallas_v2"
            except Exception as e:  # noqa: BLE001 - kernel plane
                gear_pallas.mark_v2_broken(e)
        if entry is None and gear_pallas.pallas_enabled():
            # Fused kernel (default on TPU; 3.4× the XLA path on v5e).
            # Restaging runs on device inside the same program; a kernel
            # failure here (sync: jit compiles at call time) downgrades
            # to the XLA path process-wide instead of degrading the
            # session — fingerprints stay available either way. The
            # live region is zero-padded to the kernel's 64 KiB row-grid
            # granularity so distinct tail-block sizes share compiles.
            try:
                words = gear_pallas.gear_bitmap_flat(
                    gear_pallas.quantize_flat(buf, halo_len, live),
                    halo_len, self.avg_bits,
                    interpret=jax.default_backend() == "cpu")
                entry = ("pallas", words, gear_pallas.nrows_for(live),
                         live, hblk, self._scanned, halo_len)
            except Exception as e:  # noqa: BLE001 - kernel plane
                gear_pallas.mark_broken(e)
        if entry is None:
            words = gear.gear_bitmap(buf, self.avg_bits)  # async dispatch
            entry = ("xla", words, halo_len, live, hblk, self._scanned)
        if scan_backend is None:
            scan_backend = entry[0]
        metrics.counter_add("makisu_gear_scan_bytes_total", live,
                            backend=scan_backend)
        self._inflight.append(entry)
        self._scanned += live
        # Next block's halo: the last HALO live bytes (padding excluded;
        # byte-identical to the old (halo+blk)[-HALO:]).
        end = halo_len + live
        self._halo = hblk[max(0, end - gear_pallas.HALO):end]
        while len(self._inflight) > self._depth:
            self._process_block(self._inflight.pop(0))

    def _scan_positions(self, hblk: bytes, halo_len: int, live: int):
        """Candidate positions for one block (native C++ scan): the
        shared math of the synchronous and pooled routes — positions
        over the halo-prefixed buffer, trimmed to the live region,
        halo-relative."""
        from makisu_tpu import native
        buf = np.frombuffer(hblk, dtype=np.uint8)
        pos = native.gear_scan_positions(
            buf, self._table, (1 << self.avg_bits) - 1)
        lo = np.searchsorted(pos, halo_len)
        hi = np.searchsorted(pos, halo_len + live)
        return pos[lo:hi] - halo_len

    def _scan_task(self, hblk: bytes, halo_len: int, live: int):
        t0 = time.monotonic()
        try:
            return self._scan_positions(hblk, halo_len, live)
        finally:
            metrics.stage_busy_add("gear_scan", time.monotonic() - t0)

    def _process_block(self, entry: tuple) -> None:
        """Read back one block's bitmap (bounded sync) and cut chunks."""
        kind, words, meta, live, hblk, base = entry[:6]
        if kind == "native":
            halo_len = meta
            if hasattr(words, "result"):
                # Pooled scan: block until THIS block's candidates are
                # in (stream order preserved; a task error propagates
                # here and degrades the session like any scan failure).
                words = words.result()
            candidates = words.astype(np.int64) + base  # host positions
        elif kind == "pallas":
            from makisu_tpu.ops import gear_pallas
            host_words = _backend.sync_bounded(
                words, "gear bitmap readback")
            nrows = meta
            halo_len = entry[6]
            bits = gear.unpack_bits_np(
                host_words[:nrows], nrows * gear_pallas.ROW)
            candidates = np.nonzero(
                bits.reshape(-1)[:live])[0] + base
        else:
            host_words = _backend.sync_bounded(
                words, "gear bitmap readback")
            halo_len = meta
            bits = gear.unpack_bits_np(
                host_words, halo_len + live)[halo_len:halo_len + live]
            candidates = np.nonzero(bits)[0] + base
        with memoryview(hblk) as mv:
            self._tail.extend(mv[halo_len:halo_len + live])
        # tolist(): one C conversion instead of a numpy-scalar __int__
        # per candidate on the producer's critical path.
        for pos in candidates.tolist():
            self._cut_to(pos + 1)  # cut AFTER the boundary byte
        # Oversize uncut span without candidates: force max-size cuts.
        # (Measured from the last cut, not the tail start — on the
        # batched routes the tail also holds pending batch bytes.)
        while (self._tail_offset + len(self._tail) - self._prev_cut
               > self.max_size):
            self._force_cut(self._prev_cut + self.max_size)

    def _cut_to(self, end: int) -> None:
        if end - self._prev_cut < self.min_size:
            return
        while end - self._prev_cut > self.max_size:
            self._force_cut(self._prev_cut + self.max_size)
        if end - self._prev_cut >= self.min_size:
            self._take(end)

    def _force_cut(self, end: int) -> None:
        self._take(end)

    def _take(self, end: int) -> None:
        n = end - self._prev_cut
        if n <= 0:
            return
        if ((self._pool is not None or self._sha_sync)
                and self._degraded is None):
            # Batched fast path (pooled AND serial-native): no per-chunk
            # byte shuffling at all. Chunks tile the stream, so the
            # pending batch IS the tail's prefix [_tail_offset,
            # _prev_cut) — _take just records (offset, length) and the
            # flush consumes that prefix in ONE slice + ONE native call
            # (the old per-chunk memoryview copies were ~2s/GB of pure
            # Python on the serial route).
            self._sha_meta.append((self._prev_cut, n))
            self._native_hashed += n
            self._prev_cut = end
            if end - self._tail_offset >= SHA_BATCH_BYTES:
                self._flush_sha_batch()
            return
        # Immediate path (device lanes / service / per-chunk hashlib):
        # nothing defers here, so the tail starts at the chunk start
        # (_prev_cut == _tail_offset) and is consumed chunk by chunk.
        # The memoryview must close before the del: a bytearray with an
        # exported buffer cannot resize.
        with memoryview(self._tail) as mv:
            data = bytes(mv[:n])
        del self._tail[:n]
        self._emit(data, self._tail_offset)
        self._tail_offset = end
        self._prev_cut = end

    def _notify(self, hex_digest: str) -> None:
        """Stream one fingerprint to the bound observer (chunk-dedup
        cache prefetch). Never raises: a cache-side hiccup must not
        degrade fingerprinting."""
        if self._observer is None:
            return
        try:
            self._observer(hex_digest)
        except Exception:  # noqa: BLE001 - observer plane
            self._observer = None  # one failure disables, not N

    def _flush_sha_batch(self) -> None:
        if not self._sha_meta:
            return
        meta = self._sha_meta
        self._sha_meta = []
        # The batch is the tail prefix the recorded chunks tile:
        # [_tail_offset, _prev_cut) in stream coordinates.
        consumed = self._prev_cut - self._tail_offset
        lengths = [n for _, n in meta]
        if self._pool is None:
            # Serial native route: hash the batch NOW — ONE
            # GIL-released native call (runtime-dispatched: SHA-NI
            # multi-buffer / EVP / scalar) straight out of the tail
            # buffer, zero-copy (nothing mutates the tail during a
            # synchronous call). Digests are byte-identical to hashlib.
            from makisu_tpu import native
            with memoryview(self._tail) as mv:
                digests = native.sha256_batch(mv[:consumed], lengths)
            del self._tail[:consumed]
            self._tail_offset = self._prev_cut
            raw = digests.tobytes()  # ONE copy; bytes slicing is cheap
            if self._observer is None:
                self._chunks.extend(
                    Chunk(off, n, raw[32 * i:32 * i + 32])
                    for i, (off, n) in enumerate(meta))
            else:
                for i, (off, n) in enumerate(meta):
                    digest = raw[32 * i:32 * i + 32]
                    self._chunks.append(Chunk(off, n, digest))
                    self._notify(digest.hex())
            return
        # Pooled route: copy the prefix ONCE into the task's own buffer
        # (the producer keeps mutating the tail while the task runs).
        with memoryview(self._tail) as mv:
            buf = bytes(mv[:consumed])
        del self._tail[:consumed]
        self._tail_offset = self._prev_cut
        self._sha_slots.acquire()  # released by the task (backpressure)
        with self._sha_depth_lock:
            self._sha_depth += 1
            depth = self._sha_depth
        metrics.stage_queue_depth("chunk_sha", depth)
        self._sha_pending.append(
            (meta, concurrency.submit_ctx(self._pool, self._sha_task,
                                          buf, lengths)))

    def _sha_task(self, buf: bytes, lengths: list[int]):
        """Pool-side chunk hashing: ONE GIL-released native call for
        the whole batch (digests byte-identical to hashlib — same
        OpenSSL underneath). Deliberately does nothing else: every
        extra GIL acquisition on a pool thread can stall a full switch
        interval behind the GIL-bound producer, so batch assembly
        happens in _take/_flush_sha_batch and Chunk objects are built
        at finish()."""
        from makisu_tpu import native
        t0 = time.monotonic()
        try:
            digests = native.sha256_batch(buf, lengths)
            if self._observer is not None:
                for row in digests:
                    self._notify(row.tobytes().hex())
            return digests
        finally:
            with self._sha_depth_lock:
                self._sha_depth -= 1
            self._sha_slots.release()
            metrics.stage_busy_add("chunk_sha", time.monotonic() - t0)

    def _emit(self, data: bytes, offset: int) -> None:
        if self._native:
            # Per-chunk hashlib: the no-batch-symbol fallback (a stale
            # library without gear_sha256_batch). The batched routes
            # never reach here — _take records chunks for the prefix
            # flush instead of materializing per-chunk bytes.
            import hashlib
            self._native_hashed += len(data)
            digest = hashlib.sha256(data).digest()
            self._chunks.append(Chunk(offset, len(data), digest))
            self._notify(digest.hex())
            return
        if self.service is not None:
            self._service_pending.append(
                (offset, len(data),
                 self.service.submit(data, owner=id(self))))
            return
        for b in self._batchers:
            if len(data) <= b.cap - 64:  # leave room for sha padding
                b.add(offset, memoryview(data))
                return
        raise AssertionError(
            f"chunk of {len(data)} bytes exceeds every lane bucket")
