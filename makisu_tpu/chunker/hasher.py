"""Hasher implementations behind the layer-commit seam.

``LayerSink`` is the writable object a layer tar streams into; ``finish()``
yields the layer's identity: tar digest (diffID), gzip blob descriptor, and
(TPU path) content-defined chunk fingerprints.

Reference hot path replaced: lib/builder/step/common.go tarAndGzipDiffs:35
(tar bytes → two sequential SHA-256 digesters + pgzip via nested
ConcurrentMultiWriters).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import BinaryIO, Protocol

from makisu_tpu import tario
from makisu_tpu.docker.image import (
    MEDIA_TYPE_LAYER,
    Descriptor,
    Digest,
    DigestPair,
)


@dataclasses.dataclass(frozen=True)
class ChunkFingerprint:
    offset: int
    length: int
    hex_digest: str


@dataclasses.dataclass
class LayerCommit:
    """Everything the cache/registry need to know about one layer."""

    digest_pair: DigestPair
    chunks: list[ChunkFingerprint]
    # Compression identity the blob was written with (cache entries
    # record it so chunk reconstitution replays byte-identically).
    gzip_backend_id: str = ""

    @property
    def chunk_ids(self) -> list[str]:
        return [c.hex_digest for c in self.chunks]


class _TeeDigest:
    """File-like fanning writes to a digest and an underlying file."""

    def __init__(self, out: BinaryIO) -> None:
        self.out = out
        self.digest = hashlib.sha256()
        self.size = 0

    def write(self, data: bytes) -> int:
        self.digest.update(data)
        self.size += len(data)
        return self.out.write(data)

    def flush(self) -> None:
        self.out.flush()


class LayerSink:
    """CPU layer sink: gzip + (tar digest, gzip digest) streaming.

    Subclasses tap the uncompressed tar stream for extra work.

    On multicore hosts, compression runs on a worker thread behind a
    bounded queue so the tar digest (and TPU tap) overlap with gzip —
    the reference's ConcurrentMultiWriter fan-out
    (lib/stream/multi_writer.go:25, lib/builder/step/common.go:47-56).
    Both hashlib and zlib release the GIL, so the overlap is real.
    """

    def __init__(self, out: BinaryIO, backend_id: str | None = None,
                 threaded: bool | None = None) -> None:
        import os as _os
        self._tar_digest = hashlib.sha256()
        self._tee = _TeeDigest(out)
        self.backend_id = backend_id or tario.gzip_backend_id()
        self._gz = tario.gzip_writer(self._tee, backend_id=self.backend_id)
        self._closed = False
        if threaded is None:
            threaded = (_os.cpu_count() or 1) > 1
        self._queue = None
        self._worker = None
        self._worker_error: list[BaseException] = []
        if threaded:
            import queue
            import threading
            self._queue = queue.Queue(maxsize=8)

            def run() -> None:
                while True:
                    item = self._queue.get()
                    if item is None:
                        return
                    try:
                        self._gz.write(item)
                    except BaseException as e:  # noqa: BLE001
                        self._worker_error.append(e)
                        return

            self._worker = threading.Thread(target=run, daemon=True)
            self._worker.start()

    def write(self, data: bytes) -> int:
        if self._worker_error:
            raise RuntimeError("layer compression failed") \
                from self._worker_error[0]
        if self._queue is not None:
            # Bounded put that re-checks for a dead worker: if the
            # compressor thread died while the queue was full, a plain
            # put() would block forever and hang the build instead of
            # surfacing the error.
            import queue as queue_mod
            while True:
                try:
                    self._queue.put(bytes(data), timeout=1.0)
                    break
                except queue_mod.Full:
                    if self._worker_error:
                        raise RuntimeError("layer compression failed") \
                            from self._worker_error[0]
        self._tar_digest.update(data)
        if self._queue is None:
            self._gz.write(data)
        self._tap(data)
        return len(data)

    def _tap(self, data: bytes) -> None:  # pragma: no cover - hook
        pass

    def _finish_chunks(self) -> list[ChunkFingerprint]:
        return []

    def finish(self) -> LayerCommit:
        if self._closed:
            raise RuntimeError("layer sink already finished")
        self._closed = True
        if self._queue is not None:
            # Same bounded put as write(): a worker that died with the
            # queue full must surface its error, not hang the build.
            import queue as queue_mod
            while True:
                try:
                    self._queue.put(None, timeout=1.0)
                    break
                except queue_mod.Full:
                    if self._worker_error:
                        raise RuntimeError("layer compression failed") \
                            from self._worker_error[0]
            self._worker.join()
            if self._worker_error:
                raise RuntimeError("layer compression failed") \
                    from self._worker_error[0]
        self._gz.close()
        self._tee.flush()
        pair = DigestPair(
            tar_digest=Digest.from_hex(self._tar_digest.hexdigest()),
            gzip_descriptor=Descriptor(
                MEDIA_TYPE_LAYER, self._tee.size,
                Digest.from_hex(self._tee.digest.hexdigest())))
        return LayerCommit(pair, self._finish_chunks(),
                           gzip_backend_id=self.backend_id)


class Hasher(Protocol):
    """Factory for layer sinks; chosen once per build."""

    name: str

    def open_layer(self, out: BinaryIO,
                   backend_id: str | None = None) -> LayerSink: ...


class CPUHasher:
    """Parity with the reference: digests only, no chunking."""

    name = "cpu"

    def open_layer(self, out: BinaryIO,
                   backend_id: str | None = None) -> LayerSink:
        return LayerSink(out, backend_id=backend_id)


class _TPUSink(LayerSink):
    def __init__(self, out: BinaryIO, session,
                 backend_id: str | None = None) -> None:
        super().__init__(out, backend_id=backend_id)
        self._session = session

    def _tap(self, data: bytes) -> None:
        self._session.update(data)

    def _finish_chunks(self) -> list[ChunkFingerprint]:
        return [ChunkFingerprint(c.offset, c.length, c.hex)
                for c in self._session.finish()]


class TPUHasher:
    """CPU digests + accelerator-side CDC chunk fingerprints.

    ``shared=True`` routes chunk hashing through the process-wide
    HashService so concurrent builds fill common device batches
    (worker/build-farm mode).
    """

    name = "tpu"

    def __init__(self, avg_bits: int | None = None,
                 min_size: int | None = None,
                 max_size: int | None = None,
                 shared: bool = False) -> None:
        from makisu_tpu.ops import gear
        self.avg_bits = avg_bits or gear.DEFAULT_AVG_BITS
        self.min_size = min_size or gear.DEFAULT_MIN_SIZE
        self.max_size = max_size or gear.DEFAULT_MAX_SIZE
        self.shared = shared

    def open_layer(self, out: BinaryIO,
                   backend_id: str | None = None) -> LayerSink:
        from makisu_tpu.chunker.cdc import ChunkSession
        service = None
        if self.shared:
            from makisu_tpu.chunker.service import shared_service
            service = shared_service()
        return _TPUSink(out, ChunkSession(
            self.avg_bits, self.min_size, self.max_size, service=service),
            backend_id=backend_id)


def get_hasher(name: str) -> Hasher:
    import os
    if name == "cpu":
        return CPUHasher()
    if name == "tpu":
        # Worker mode sets MAKISU_TPU_SHARED_HASH so concurrent builds
        # batch onto the shared device stream.
        return TPUHasher(
            shared=os.environ.get("MAKISU_TPU_SHARED_HASH") == "1")
    raise ValueError(f"unknown hasher {name!r} (choose cpu or tpu)")
