"""Hasher implementations behind the layer-commit seam.

``LayerSink`` is the writable object a layer tar streams into; ``finish()``
yields the layer's identity: tar digest (diffID), gzip blob descriptor, and
(TPU path) content-defined chunk fingerprints.

Reference hot path replaced: lib/builder/step/common.go tarAndGzipDiffs:35
(tar bytes → two sequential SHA-256 digesters + pgzip via nested
ConcurrentMultiWriters).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import BinaryIO, Protocol

from makisu_tpu import tario
from makisu_tpu.docker.image import (
    MEDIA_TYPE_LAYER,
    Descriptor,
    Digest,
    DigestPair,
)
from makisu_tpu.utils import events, metrics


@dataclasses.dataclass(frozen=True)
class ChunkFingerprint:
    offset: int
    length: int
    hex_digest: str


@dataclasses.dataclass
class LayerCommit:
    """Everything the cache/registry need to know about one layer."""

    digest_pair: DigestPair
    chunks: list[ChunkFingerprint]
    # Compression identity the blob was written with (cache entries
    # record it so chunk reconstitution replays byte-identically).
    gzip_backend_id: str = ""

    @property
    def chunk_ids(self) -> list[str]:
        return [c.hex_digest for c in self.chunks]


class LayerSink:
    """CPU layer sink: gzip + (tar digest, gzip digest) streaming.

    Subclasses tap the uncompressed tar stream for extra work.

    On multicore hosts, compression runs on a worker thread behind a
    bounded queue so the tar digest (and TPU tap) overlap with gzip —
    the reference's ConcurrentMultiWriter fan-out
    (lib/stream/multi_writer.go:25, lib/builder/step/common.go:47-56).
    Both hashlib and zlib release the GIL, so the overlap is real.
    With the pgzip backend the writer behind the queue is itself the
    block-parallel compress stage (tario.BlockGzipWriter): deflate
    fans out across the shared hash pool at ``compress_workers()``
    lanes, byte-identical at every count.
    """

    def __init__(self, out: BinaryIO, backend_id: str | None = None,
                 threaded: bool | None = None) -> None:
        import os as _os
        self._tar_digest = hashlib.sha256()
        self._nbytes = 0  # uncompressed bytes digested (telemetry)
        self._writes = 0  # queue-depth sampling stride
        self._tee = tario.TeeDigest(out)
        self.backend_id = backend_id or tario.gzip_backend_id()
        self._gz = tario.gzip_writer(self._tee, backend_id=self.backend_id)
        self._closed = False
        if threaded is None:
            threaded = (_os.cpu_count() or 1) > 1
        self._queue = None
        self._worker = None
        self._worker_error: list[BaseException] = []
        if threaded:
            import contextvars
            import queue
            import threading
            import time as _time
            self._queue = queue.Queue(maxsize=8)

            # A block-parallel writer (tario.BlockGzipWriter) reports
            # its own compress busy seconds from its pool lanes; this
            # feed thread's write() is then just buffering + batch
            # submission, and charging it too would double-count the
            # stage.
            self_reporting = getattr(self._gz, "reports_compress_busy",
                                     False)

            def run() -> None:
                # Busy time accumulates locally and flushes once at
                # stream end — per-write counter churn would become
                # the overhead it measures.
                busy = 0.0
                try:
                    while True:
                        item = self._queue.get()
                        if item is None:
                            return
                        t0 = _time.monotonic()
                        try:
                            self._gz.write(item)
                        except BaseException as e:  # noqa: BLE001
                            self._worker_error.append(e)
                            return
                        busy += _time.monotonic() - t0
                finally:
                    if not self_reporting:
                        metrics.stage_busy_add(metrics.COMPRESS_STAGE,
                                               busy)

            # copy_context: the stage counter must land in the build's
            # registry, not just the process-global one (threads start
            # with an empty context).
            self._worker = threading.Thread(
                target=contextvars.copy_context().run, args=(run,),
                daemon=True)
            self._worker.start()

    def _put_checked(self, item) -> None:
        """Bounded put that re-checks for a dead worker: if the
        compressor thread died while the queue was full, a plain put()
        would block forever and hang the build instead of surfacing
        the error."""
        import queue as queue_mod
        while True:
            try:
                self._queue.put(item, timeout=1.0)
                return
            except queue_mod.Full:
                if self._worker_error:
                    raise RuntimeError("layer compression failed") \
                        from self._worker_error[0]

    def write(self, data: bytes) -> int:
        if self._worker_error:
            raise RuntimeError("layer compression failed") \
                from self._worker_error[0]
        if self._queue is not None:
            # The queue hands data to the compressor thread AFTER this
            # call returns, so a mutable buffer (bytearray, memoryview
            # a tar writer recycles) must be copied — but immutable
            # bytes, the overwhelmingly common case, can be enqueued
            # as-is: a per-write copy on the layer hot path.
            self._put_checked(data if isinstance(data, bytes)
                              else bytes(data))
            self._writes += 1
            if not self._writes & 0xFF:  # sampled: writes are ~16KiB
                metrics.stage_queue_depth("compress",
                                          self._queue.qsize())
        self._tar_digest.update(data)
        self._nbytes += len(data)
        if self._queue is None:
            self._gz.write(data)
        self._tap(data)
        # Hashing a huge layer is minutes of pure CPU with no events
        # or logs; each landed buffer stamps the progress clock so the
        # stall watchdog never mistakes a hard-working commit for a
        # wedge (same discipline as httputil's stream loop).
        events.note_progress()
        return len(data)

    def _tap(self, data: bytes) -> None:  # pragma: no cover - hook
        pass

    def _finish_chunks(self) -> list[ChunkFingerprint]:
        return []

    def open_tar(self):
        """Tar writer whose stream feeds this sink (the commit path's
        single entry point for layer serialization)."""
        import tarfile
        return tarfile.open(fileobj=self, mode="w|")

    def finish(self) -> LayerCommit:
        if self._closed:
            raise RuntimeError("layer sink already finished")
        self._closed = True
        if self._queue is not None:
            self._put_checked(None)
            self._worker.join()
            if self._worker_error:
                raise RuntimeError("layer compression failed") \
                    from self._worker_error[0]
        self._gz.close()
        self._tee.flush()
        pair = DigestPair(
            tar_digest=Digest.from_hex(self._tar_digest.hexdigest()),
            gzip_descriptor=Descriptor(
                MEDIA_TYPE_LAYER, self._tee.size,
                Digest.from_hex(self._tee.digest.hexdigest())))
        metrics.counter_add("makisu_bytes_hashed_total", self._nbytes,
                            backend="python", path="layer_sink")
        backend = self.backend_id.split("-", 1)[0]
        metrics.counter_add(metrics.COMPRESS_BYTES, self._nbytes,
                            backend=backend, direction="in")
        metrics.counter_add(metrics.COMPRESS_BYTES, self._tee.size,
                            backend=backend, direction="out")
        return LayerCommit(pair, self._finish_chunks(),
                           gzip_backend_id=self.backend_id)


class _NativeTarWriter:
    """tarfile.TarFile-shaped writer over the native pipeline: headers
    are rendered by Python's tarfile (byte-identical PAX output); file
    content, padding, hashing, and compression run in C++."""

    import tarfile as _tarfile
    _FMT = (_tarfile.PAX_FORMAT, _tarfile.ENCODING, "surrogateescape")

    def __init__(self, sink: "NativeLayerSink") -> None:
        self._sink = sink
        self._offset = 0
        self._closed = False

    def addfile(self, tarinfo, fileobj=None) -> None:
        buf = tarinfo.tobuf(*self._FMT)
        self._sink._handle.write(buf)
        self._offset += len(buf)
        if fileobj is not None:
            remaining = tarinfo.size
            while remaining > 0:
                chunk = fileobj.read(min(remaining, 1 << 20))
                if not chunk:
                    raise OSError(f"{tarinfo.name}: short read")
                self._sink._handle.write(chunk)
                remaining -= len(chunk)
            pad = (512 - tarinfo.size % 512) % 512
            if pad:
                self._sink._handle.write(b"\0" * pad)
            self._offset += tarinfo.size + pad

    def add_path(self, tarinfo, path: str) -> None:
        """Fast path: content streams through C++ (no Python bytes)."""
        buf = tarinfo.tobuf(*self._FMT)
        self._sink._handle.write(buf)
        self._sink._handle.write_file(path, tarinfo.size)
        pad = (512 - tarinfo.size % 512) % 512
        self._offset += len(buf) + tarinfo.size + pad

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # End of archive exactly as tarfile: two zero blocks, then pad
        # the stream to a RECORDSIZE multiple (cache-identity-bearing).
        import tarfile
        end = b"\0" * (2 * tarfile.BLOCKSIZE)
        rem = (self._offset + len(end)) % tarfile.RECORDSIZE
        if rem:
            end += b"\0" * (tarfile.RECORDSIZE - rem)
        self._offset += len(end)
        self._sink._handle.write(end)
        # The writer streams straight into the C++ handle, bypassing
        # sink.write — account its bytes for the sink's telemetry.
        self._sink._nbytes += self._offset

    def __enter__(self) -> "_NativeTarWriter":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        if exc_type is None:
            self.close()


class NativeLayerSink:
    """Layer sink backed by native/layersink.cpp: the whole per-byte
    pipeline (tar framing, dual sha256, gzip) runs in C++. With a
    ``session`` (TPU hasher) the uncompressed stream additionally taps
    into the chunker via a native callback, so CDC fingerprinting rides
    the same single pass."""

    def __init__(self, out: BinaryIO, backend_id: str | None = None,
                 session=None) -> None:
        from makisu_tpu import native
        from makisu_tpu.utils import concurrency
        self.backend_id = backend_id or tario.gzip_backend_id()
        self._nbytes = 0  # uncompressed bytes digested (telemetry)
        parts = self.backend_id.split("-")
        backend, level = parts[0], int(parts[1])
        block = int(parts[2]) if backend == "pgzip" else 0
        out.flush()  # nothing buffered may trail the native fd writes
        # The compress-workers knob governs the C++ block pool too —
        # same worker-count-is-throughput-only contract as the Python
        # stage (block bytes are a pure function of level/block size).
        self._handle = native.LayerSinkHandle(
            out.fileno(), backend, level, block or native.DEFAULT_BLOCK,
            nthreads=concurrency.compress_workers())
        self._session = session
        if session is not None:
            self._handle.set_tap(session.update)

    def open_tar(self) -> _NativeTarWriter:
        return _NativeTarWriter(self)

    def write(self, data: bytes) -> int:  # parity with LayerSink
        self._handle.write(bytes(data))
        self._nbytes += len(data)
        events.note_progress()  # hashing is progress (see LayerSink)
        return len(data)

    def finish(self) -> LayerCommit:
        tar_hex, gz_hex, gz_size, _ = self._handle.finish()
        self._handle.close()
        metrics.counter_add("makisu_bytes_hashed_total", self._nbytes,
                            backend="native", path="layer_sink")
        backend = self.backend_id.split("-", 1)[0]
        metrics.counter_add(metrics.COMPRESS_BYTES, self._nbytes,
                            backend=backend, direction="in")
        metrics.counter_add(metrics.COMPRESS_BYTES, gz_size,
                            backend=backend, direction="out")
        pair = DigestPair(
            tar_digest=Digest.from_hex(tar_hex),
            gzip_descriptor=Descriptor(MEDIA_TYPE_LAYER, gz_size,
                                       Digest.from_hex(gz_hex)))
        chunks = []
        if self._session is not None:
            chunks = [ChunkFingerprint(c.offset, c.length, c.hex)
                      for c in self._session.finish()]
        return LayerCommit(pair, chunks, gzip_backend_id=self.backend_id)


class Hasher(Protocol):
    """Factory for layer sinks; chosen once per build."""

    name: str

    def open_layer(self, out: BinaryIO,
                   backend_id: str | None = None) -> LayerSink: ...


def _native_sink_enabled() -> bool:
    import os
    if os.environ.get("MAKISU_TPU_NATIVE_SINK") == "0":
        return False
    from makisu_tpu import native
    return native.layersink_available()


def _use_native(out: BinaryIO, backend_id: str | None = None) -> bool:
    """One decision point for native-vs-Python pipelines (the choice is
    cache-identity-neutral but must be consistent across hashers):
    native needs a real fd; in-memory outputs (tests) take Python.

    zlib level 0 is excluded: stored-block framing depends on write
    granularity, and the C++ pipeline feeds deflate at a different
    granularity than the (pinned, see tario._FixedGranularityWriter)
    Python path — choosing native there would split cache identity by
    host capability."""
    if not _native_sink_enabled():
        return False
    if (backend_id or tario.gzip_backend_id()) == "zlib-0":
        return False
    try:
        out.fileno()
    except (OSError, AttributeError, ValueError):
        return False
    return True


class CPUHasher:
    """Parity with the reference: digests only, no chunking. Uses the
    native C++ pipeline when available (MAKISU_TPU_NATIVE_SINK=0 forces
    the pure-Python path)."""

    name = "cpu"

    def open_layer(self, out: BinaryIO,
                   backend_id: str | None = None) -> LayerSink:
        if _use_native(out, backend_id):
            return NativeLayerSink(out, backend_id=backend_id)
        return LayerSink(out, backend_id=backend_id)


class _TPUSink(LayerSink):
    def __init__(self, out: BinaryIO, session,
                 backend_id: str | None = None) -> None:
        super().__init__(out, backend_id=backend_id)
        self._session = session

    def _tap(self, data: bytes) -> None:
        self._session.update(data)

    def _finish_chunks(self) -> list[ChunkFingerprint]:
        return [ChunkFingerprint(c.offset, c.length, c.hex)
                for c in self._session.finish()]


class TPUHasher:
    """CPU digests + accelerator-side CDC chunk fingerprints.

    ``shared=True`` routes chunk hashing through the process-wide
    HashService so concurrent builds fill common device batches
    (worker/build-farm mode).
    """

    name = "tpu"

    def __init__(self, avg_bits: int | None = None,
                 min_size: int | None = None,
                 max_size: int | None = None,
                 shared: bool = False) -> None:
        from makisu_tpu.ops import gear
        self.avg_bits = avg_bits or gear.DEFAULT_AVG_BITS
        self.min_size = min_size or gear.DEFAULT_MIN_SIZE
        self.max_size = max_size or gear.DEFAULT_MAX_SIZE
        self.shared = shared

    def open_layer(self, out: BinaryIO,
                   backend_id: str | None = None) -> LayerSink:
        from makisu_tpu.chunker.cdc import ChunkSession
        service = None
        if self.shared:
            from makisu_tpu.chunker.service import shared_service
            service = shared_service()
        session = ChunkSession(self.avg_bits, self.min_size,
                               self.max_size, service=service)
        if _use_native(out, backend_id):
            # Native pipeline + chunker tap: one pass does tar framing,
            # digests, gzip (C++) AND CDC intake (device).
            return NativeLayerSink(out, backend_id=backend_id,
                                   session=session)
        return _TPUSink(out, session, backend_id=backend_id)


def get_hasher(name: str) -> Hasher:
    import os
    if name == "cpu":
        return CPUHasher()
    if name == "tpu":
        # Worker mode sets MAKISU_TPU_SHARED_HASH so concurrent builds
        # batch onto the shared device stream.
        return TPUHasher(
            shared=os.environ.get("MAKISU_TPU_SHARED_HASH") == "1")
    raise ValueError(f"unknown hasher {name!r} (choose cpu or tpu)")
