"""The layer-commit hashing seam.

Every byte of every committed layer tar flows through a ``Hasher`` here —
the exact splice point of the reference's hot loop (tarAndGzipDiffs,
lib/builder/step/common.go:35-64). Two implementations:

- ``CPUHasher``: dual streaming SHA-256 (tar diffID + gzip blob digest)
  plus gzip, byte-for-byte what the reference computes.
- ``TPUHasher``: the CPU pair plus Gear content-defined chunking and
  lane-parallel per-chunk SHA-256 on the accelerator (ops/gear, ops/sha256
  via chunker.cdc), producing chunk fingerprints for the chunk-granular
  distributed cache (the reference caches whole layers only,
  lib/cache/cache_manager.go:39-40).
"""

from makisu_tpu.chunker.hasher import (
    ChunkFingerprint,
    CPUHasher,
    Hasher,
    LayerCommit,
    LayerSink,
    TPUHasher,
    get_hasher,
)

__all__ = [
    "ChunkFingerprint", "CPUHasher", "Hasher", "LayerCommit", "LayerSink",
    "TPUHasher", "get_hasher",
]
