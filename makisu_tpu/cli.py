"""makisu-tpu command line: build / pull / push / diff / version.

Reference surface: bin/makisu/cmd/ (root.go:73-87). Subcommands are filled
in as their subsystems land; ``version`` is always available.
"""

from __future__ import annotations

import argparse
import sys

import makisu_tpu


def make_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="makisu-tpu",
        description="TPU-native daemonless container image builder.")
    parser.add_argument("--log-level", default="info",
                        choices=["debug", "info", "warn", "error"])
    parser.add_argument("--log-fmt", default="json",
                        choices=["json", "console"])
    sub = parser.add_subparsers(dest="command")
    sub.add_parser("version", help="print the build version")
    return parser


def main(argv: list[str] | None = None) -> int:
    parser = make_parser()
    args = parser.parse_args(argv)
    if args.command == "version":
        print(makisu_tpu.BUILD_HASH)
        return 0
    parser.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
