"""makisu-tpu command line: build / pull / push / diff / version.

Reference surface: bin/makisu/cmd/ (root.go:73-87; build flags
build.go:97-135; helpers utils.go:41-224; pull.go, push.go, diff.go,
version.go). One addition over the reference: ``--hasher cpu|tpu``
selects the layer-commit hashing backend (the TPU path also records
chunk fingerprints into the distributed cache).
"""

from __future__ import annotations

import argparse
import contextvars
import cProfile
import os
import sys

import makisu_tpu
from makisu_tpu import tario
from makisu_tpu.utils import concurrency
from makisu_tpu.utils import events
from makisu_tpu.utils import logging as log
from makisu_tpu.utils import metrics
from makisu_tpu.utils import pathutils

# How this invocation was launched, for the build_info gauge. The
# worker sets "worker" around each in-process cli.main call —
# context-scoped, not process env, so a process that hosts a worker
# AND runs standalone builds labels each correctly.
invocation_mode: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "makisu_invocation_mode", default="standalone")


def make_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="makisu-tpu",
        description="TPU-native daemonless container image builder.")
    parser.add_argument("--log-level", default="info",
                        choices=["debug", "info", "warn", "error"])
    parser.add_argument("--log-output", default="stdout")
    parser.add_argument("--log-fmt", default="json",
                        choices=["json", "console"])
    parser.add_argument("--cpu-profile", action="store_true",
                        help="write a cProfile dump to /tmp/makisu-tpu.prof")
    parser.add_argument("--transfer-concurrency", type=int, default=0,
                        metavar="N",
                        help="parallel registry transfers (pulls, pushes, "
                             "chunk fetches) across the whole process "
                             "(default 8)")
    parser.add_argument("--transfer-memory-budget", type=int, default=0,
                        metavar="MB",
                        help="cap on transfer bytes resident in memory at "
                             "once, across all parallel transfers "
                             "(default 256)")
    parser.add_argument("--hash-workers", type=int, default=0,
                        metavar="N",
                        help="layer-commit pipeline workers: file "
                             "read-ahead, parallel gear block scans, "
                             "and pooled chunk SHA-256 overlap on N "
                             "threads (default min(8, cpu) on >=4-core "
                             "hosts, serial below; 1 = the serial "
                             "pipeline; env MAKISU_TPU_HASH_WORKERS)")
    parser.add_argument("--compress-workers", type=int, default=0,
                        metavar="N",
                        help="block-parallel compress lanes for the "
                             "pgzip backend (and the native sink's C++ "
                             "block pool); bytes are identical at every "
                             "count (default min(8, cpu); env "
                             "MAKISU_TPU_COMPRESS_WORKERS)")
    parser.add_argument("--hash-linger-ms", type=float, default=-1.0,
                        metavar="MS",
                        help="shared hash-service batch linger in "
                             "milliseconds (worker-mode cross-build "
                             "device batching; default 2; env "
                             "MAKISU_TPU_HASH_LINGER_MS)")
    parser.add_argument("--metrics-out", default="", metavar="FILE",
                        help="write a JSON telemetry report (span tree + "
                             "counters) for this command to FILE")
    parser.add_argument("--events-out", default="", metavar="FILE",
                        help="write this command's build events (JSONL, "
                             "one event per line) to FILE as they happen")
    parser.add_argument("--explain-out", default="", metavar="FILE",
                        help="write this command's cache-decision ledger "
                             "(JSONL, schema makisu-tpu.ledger.v1: one "
                             "line per cache consult with verdict/reason/"
                             "blame, plus a summary line) to FILE — the "
                             "input `makisu-tpu explain` renders")
    parser.add_argument("--history-out", default="", metavar="FILE",
                        help="append one compact build-history record "
                             "(JSONL, schema makisu-tpu.history.v1: "
                             "duration, phase self-times, cache "
                             "economics, ISA route) to FILE after "
                             "build/pull/push commands; without it, "
                             "records land in $MAKISU_TPU_HISTORY_DIR/"
                             "history.jsonl when set — the input "
                             "`makisu-tpu history` renders")
    parser.add_argument("--diag-out", default="", metavar="FILE",
                        help="write a JSON diagnostic bundle (flight-"
                             "recorder ring, open spans, thread stacks, "
                             "resource trajectory) to FILE on failure, "
                             "stall, SIGTERM, or SIGUSR1; without it, "
                             "bundles land in $MAKISU_TPU_DIAG_DIR when "
                             "set (stall/signal dumps fall back to the "
                             "tempdir)")
    parser.add_argument("--stall-timeout", type=float, default=0.0,
                        metavar="SECONDS",
                        help="arm a stall watchdog: when the event bus "
                             "and transfer engine make no progress for "
                             "this long, emit a `stall` event and dump a "
                             "diagnostic bundle (default off; env "
                             "MAKISU_TPU_STALL_TIMEOUT)")
    parser.add_argument("--trace-out", default="", metavar="FILE",
                        help="write a Chrome/Perfetto trace-event JSON of "
                             "this command's span tree to FILE")
    parser.add_argument("--jax-profile", default="", metavar="DIR",
                        help="capture a JAX/XLA profiler trace (xprof) of "
                             "the accelerator hashing path into DIR")
    parser.add_argument("--profile-hz", type=float, default=None,
                        metavar="HZ",
                        help="wall-clock sampling profiler rate for this "
                             "command (default ~67 Hz, env "
                             "MAKISU_TPU_PROFILE_HZ; 0 disables). The "
                             "sampler self-measures its overhead and "
                             "throttles to stay under a 2%% budget")
    parser.add_argument("--profile-out", default="", metavar="FILE",
                        help="write the sampled profile (schema "
                             "makisu-tpu.profile.v1: phase-attributed "
                             "folded stacks + embedded speedscope JSON) "
                             "to FILE when the command finishes — the "
                             "input `makisu-tpu profile` renders")
    sub = parser.add_subparsers(dest="command")

    build = sub.add_parser("build", help="build a docker image")
    build.add_argument("context", help="build context directory")
    build.add_argument("-t", "--tag", required=True,
                       help="image tag (repo:tag)")
    build.add_argument("-f", "--file", default="",
                       help="Dockerfile path (default <context>/Dockerfile)")
    build.add_argument("--push", action="append", default=[],
                       metavar="REGISTRY",
                       help="push the built image to this registry")
    build.add_argument("--replica", action="append", default=[],
                       help="additional tags to save/push")
    build.add_argument("--registry-config", default="",
                       help="registry config file or inline JSON")
    build.add_argument("--dest", default="",
                       help="write a docker-save tar here")
    build.add_argument("--oci-dest", default="",
                       help="write an OCI image layout here (a directory,"
                            " or an oci-archive if the path ends in .tar)"
                            " — consumable by podman/skopeo/containerd")
    build.add_argument("--target", default="",
                       help="build up to this stage only")
    build.add_argument("--build-arg", action="append", default=[],
                       metavar="K=V")
    build.add_argument("--modifyfs", action="store_true",
                       help="allow modifying the local filesystem")
    build.add_argument("--commit", default="implicit",
                       choices=["implicit", "explicit"],
                       help="layer commit policy (#!COMMIT honored in "
                            "explicit mode)")
    build.add_argument("--blacklist", action="append", default=[],
                       help="extra paths to exclude from layers")
    # Reference default: 14 days (bin/makisu/cmd/build.go:113-117).
    build.add_argument("--local-cache-ttl", default="336h")
    build.add_argument("--redis-cache-addr", default="")
    build.add_argument("--redis-cache-password", default="")
    build.add_argument("--http-cache-addr", default="")
    build.add_argument("--http-cache-header", action="append", default=[])
    build.add_argument("--docker-host",
                       default=os.environ.get("DOCKER_HOST",
                                              "unix:///var/run/docker.sock"))
    build.add_argument("--docker-version",
                       default=os.environ.get("DOCKER_VERSION", "1.21"))
    build.add_argument("--load", action="store_true",
                       help="load the image into the local docker daemon")
    build.add_argument("--storage", default="",
                       help="storage directory (default /makisu-storage or "
                            "$HOME fallback)")
    build.add_argument("--storage-budget", type=int, default=None,
                       metavar="MB",
                       help="hot-tier byte budget for the storage dir "
                            "(chunks + blobs); past it, cold objects "
                            "evict LRU after the build — chunks whose "
                            "pack has a compressed twin demote (bytes "
                            "recoverable locally), the rest refetch "
                            "via peers/registry "
                            "(MAKISU_TPU_STORAGE_BUDGET_MB; "
                            "0/unset = unbounded)")
    build.add_argument("--storage-remote", default=None,
                       metavar="DIR",
                       help="remote/object tier directory: cold packs "
                            "demote there and refetch on demand "
                            "(MAKISU_TPU_STORAGE_REMOTE)")
    build.add_argument("--compression", default="default",
                       choices=sorted(tario.COMPRESSION_LEVELS))
    build.add_argument("--gzip-backend", default="zlib",
                       choices=["zlib", "pgzip", "auto"],
                       help="layer compressor: stdlib zlib, the native "
                            "parallel block-deflate (native/libpgzip.so),"
                            " or auto (pgzip when the native library is "
                            "available, else zlib; the RESOLVED backend "
                            "is what enters cache identity)")
    build.add_argument("--preserve-root", action="store_true",
                       help="save and restore / around the build")
    build.add_argument("--root", default="/",
                       help="build filesystem root (testing)")
    build.add_argument("--hasher", default="cpu", choices=["cpu", "tpu"],
                       help="layer hashing backend; tpu adds CDC chunk "
                            "fingerprints for chunk-granular caching")
    build.add_argument("--watch", action="store_true",
                       help="stay resident after the build and rebuild "
                            "whenever context files change (inotify "
                            "when available, mtime-poll fallback); the "
                            "resident build session keeps the stat "
                            "cache, scan memos, and applied-layer "
                            "state warm so each rebuild re-scans and "
                            "re-chunks only dirtied files. Ctrl-C "
                            "exits")
    build.add_argument("--watch-interval", type=float, default=1.0,
                       metavar="SECONDS",
                       help="change-poll interval for --watch "
                            "(default 1.0; inotify hosts poll the "
                            "event queue at this cadence)")

    pull = sub.add_parser("pull", help="pull an image into the store")
    pull.add_argument("image")
    pull.add_argument("--extract", default="",
                      help="untar the pulled rootfs into this directory")
    pull.add_argument("--oci-dest", default="",
                      help="also export the pulled image as an OCI "
                           "layout (directory, or .tar oci-archive)")
    pull.add_argument("--storage", default="")
    pull.add_argument("--registry-config", default="")
    pull.add_argument("--delta", default="", metavar="SOCKET",
                      help="delta pull: layer bytes come from this "
                           "serve endpoint (a `makisu-tpu serve` or "
                           "worker unix socket) as coalesced ranged "
                           "pack fetches of only the chunks missing "
                           "from the local chunk CAS; manifest/config/"
                           "identity still come from the registry, "
                           "and any layer without a published recipe "
                           "falls back to the registry blob route")
    pull.add_argument("--report-out", default="", metavar="FILE",
                      help="write the delta-pull economics report "
                           "(bytes fetched vs full image, per-layer "
                           "routes) as JSON")

    push = sub.add_parser("push", help="push an image tar to registries")
    push.add_argument("tar_path")
    push.add_argument("-t", "--tag", required=True)
    push.add_argument("--push", action="append", default=[],
                      metavar="REGISTRY", dest="registries")
    push.add_argument("--storage", default="")
    push.add_argument("--registry-config", default="")

    diff = sub.add_parser("diff", help="compare two images")
    diff.add_argument("images", nargs=2)
    diff.add_argument("--ignore-modtime", action="store_true")
    diff.add_argument("--storage", default="")
    diff.add_argument("--registry-config", default="")

    worker = sub.add_parser("worker", help="run a long-lived build worker")
    worker.add_argument("--socket", default="/tmp/makisu-tpu-worker.sock",
                        help="unix socket to listen on")
    worker.add_argument("--max-concurrent-builds", type=int, default=0,
                        metavar="N",
                        help="cap concurrently executing builds; "
                             "arrivals beyond the cap wait in a FIFO "
                             "admission queue (instrumented: "
                             "makisu_worker_queue_depth, queue-wait/"
                             "latency histograms, GET /builds). "
                             "0 = unlimited (default; env "
                             "MAKISU_TPU_MAX_CONCURRENT_BUILDS)")
    worker.add_argument("--slo-config", default="", metavar="FILE",
                        help="SLO rule JSON (docs/SLO.md schema): "
                             "merged over the built-in worker rules "
                             "by name; evaluated on a background "
                             "thread, firing alerts at GET /alerts")
    worker.add_argument("--alert-webhook", default="", metavar="URL",
                        help="POST each alert fired/resolved "
                             "transition here as JSON (bounded "
                             "timeout; failures counted, never "
                             "blocking)")
    worker.add_argument("--storage-budget", type=int, default=None,
                        metavar="MB",
                        help="hot-tier byte budget per storage dir "
                             "this worker builds against; enforced "
                             "after each build and on the scrub "
                             "cadence (MAKISU_TPU_STORAGE_BUDGET_MB; "
                             "0/unset = unbounded)")
    worker.add_argument("--storage-remote", default=None,
                        metavar="DIR",
                        help="remote/object tier directory for cold "
                             "pack demotion "
                             "(MAKISU_TPU_STORAGE_REMOTE)")

    serve = sub.add_parser(
        "serve", help="run a chunk-native distribution endpoint over "
                      "a storage directory (signed layer recipes + "
                      "ranged pack serving for delta pulls)")
    serve.add_argument("--socket",
                       default="/tmp/makisu-tpu-serve.sock",
                       help="unix socket to listen on")
    serve.add_argument("--storage", default="",
                       help="storage directory to serve (a builder's "
                            "--storage; recipes/packs under serve/, "
                            "chunk bytes under chunks/)")

    fleet = sub.add_parser(
        "fleet", help="run the build-farm front door: route builds "
                      "across N workers by session affinity")
    fleet.add_argument("--socket",
                       default="/tmp/makisu-tpu-fleet.sock",
                       help="unix socket the front door listens on "
                            "(speaks the worker protocol — existing "
                            "clients/top/loadgen point here "
                            "unchanged)")
    fleet.add_argument("--worker", action="append", default=[],
                       metavar="SOCKET[=STORAGE]",
                       help="one fleet member's worker socket "
                            "(repeat per worker); an optional "
                            "=STORAGE overrides --storage on builds "
                            "forwarded to it (in-process fleets "
                            "modeling per-machine disks)")
    fleet.add_argument("--poll-interval", type=float, default=1.0,
                       metavar="SECONDS",
                       help="worker /healthz + /sessions poll cadence "
                            "(the affinity/liveness signal)")
    fleet.add_argument("--tenant-quota", type=int, default=0,
                       metavar="N",
                       help="per-tenant in-flight build quota at the "
                            "front door; excess builds wait (FIFO) "
                            "and the wait is recorded as a "
                            "quota_denied fleet decision "
                            "(0 = unlimited)")
    fleet.add_argument("--max-inflight-builds", type=int, default=0,
                       metavar="N",
                       help="fleet-wide in-flight cap across all "
                            "tenants (queue-depth backpressure on "
                            "top of the workers' own admission "
                            "queues; 0 = unlimited)")
    fleet.add_argument("--spillover-queue-depth", type=int, default=2,
                       metavar="N",
                       help="load score (queue depth + in-flight) at "
                            "which the consistent-hash owner of a "
                            "new context is passed over for the "
                            "least-loaded worker")
    fleet.add_argument("--slo-config", default="", metavar="FILE",
                       help="SLO rule JSON (docs/SLO.md schema): "
                            "merged over the built-in fleet rules by "
                            "name; evaluated over scheduler stats + "
                            "canary series, served at GET /alerts")
    fleet.add_argument("--alert-webhook", default="", metavar="URL",
                       help="POST each alert fired/resolved "
                            "transition here as JSON")
    fleet.add_argument("--canary-interval", type=float, default=60.0,
                       metavar="SECONDS",
                       help="synthetic canary build cadence: each "
                            "sweep builds one tiny generated context "
                            "end-to-end on every alive worker, "
                            "scoring per-worker health for "
                            "health-demoted routing (0 disables)")
    fleet.add_argument("--canary-slow-seconds", type=float,
                       default=10.0, metavar="SECONDS",
                       help="canary latency past this counts as bad "
                            "(feeds the build_latency_burn rule and "
                            "the health score)")

    alerts_p = sub.add_parser(
        "alerts", help="render a worker's or fleet front door's "
                       "active alerts (GET /alerts)")
    alerts_p.add_argument("socket",
                          help="worker or fleet unix socket to query")
    alerts_p.add_argument("--json", action="store_true",
                          dest="json_out",
                          help="print the raw /alerts JSON payload "
                               "instead of the human render")

    sessions_p = sub.add_parser(
        "sessions", help="inspect a worker's resident build sessions, "
                         "or checkpoint/restore them through the "
                         "chunk-addressed snapshot plane")
    sessions_p.add_argument("socket",
                            help="worker unix socket to query")
    sessions_p.add_argument("verb", nargs="?", default="list",
                            choices=("list", "snapshot", "restore"),
                            help="list sessions (default), snapshot "
                                 "resident sessions to the chunk CAS, "
                                 "or restore/stage a snapshot")
    sessions_p.add_argument("context", nargs="?", default="",
                            help="context dir (optional for snapshot: "
                                 "all sessions; required for restore)")
    sessions_p.add_argument("--from", dest="from_socket", default="",
                            help="restore: pull the recipe from this "
                                 "worker's socket and push it to "
                                 "SOCKET (the fleet prewarm hand-off, "
                                 "by hand)")
    sessions_p.add_argument("--json", action="store_true",
                            dest="json_out",
                            help="print raw JSON payloads")

    top = sub.add_parser(
        "top", help="live terminal view of a worker's (or fleet "
                    "front door's) builds")
    top.add_argument("--socket", default="/tmp/makisu-tpu-worker.sock",
                     help="worker unix socket to poll")
    top.add_argument("--interval", type=float, default=2.0,
                     metavar="SECONDS", help="refresh interval")
    top.add_argument("--once", action="store_true",
                     help="print a single frame and exit (no screen "
                          "clearing; for scripts)")
    top.add_argument("--count", type=int, default=0, metavar="N",
                     help="exit after N frames (0 = until interrupted)")

    loadgen = sub.add_parser(
        "loadgen", help="synthetic concurrent-build load harness "
                        "against a real worker")
    loadgen.add_argument("--socket", default="",
                         help="drive this live worker (default: spawn "
                              "an in-process worker for the run)")
    loadgen.add_argument("--concurrency", type=int, default=4,
                         metavar="N",
                         help="concurrent submission lanes")
    loadgen.add_argument("--builds", type=int, default=0, metavar="M",
                         help="total builds to run (default "
                              "2 x concurrency)")
    loadgen.add_argument("--contexts", type=int, default=0,
                         metavar="K",
                         help="distinct generated context templates "
                              "(default = concurrency, capped at it)")
    loadgen.add_argument("--files", type=int, default=16,
                         help="files per generated context")
    loadgen.add_argument("--file-kb", type=int, default=4,
                         help="KiB per generated file")
    loadgen.add_argument("--edit-churn", type=float, default=0.25,
                         metavar="FRACTION",
                         help="fraction of a lane's files append-"
                              "edited before each rebuild")
    loadgen.add_argument("--tenants", default="tenant-a,tenant-b",
                         help="comma-separated tenant mix, assigned "
                              "to lanes round-robin")
    loadgen.add_argument("--hasher", default="tpu",
                         choices=["cpu", "tpu"],
                         help="hashing backend for the synthetic "
                              "builds (tpu exercises chunk dedup + "
                              "the shared hash service)")
    loadgen.add_argument("--max-concurrent-builds", type=int,
                         default=0, metavar="N",
                         help="admission cap for the SPAWNED worker "
                              "(ignored with --socket)")
    loadgen.add_argument("--report", default="", metavar="FILE",
                         help="write the structured JSON report "
                              "(schema makisu-tpu.loadgen.v1) here")
    loadgen.add_argument("--work-dir", default="",
                         help="working directory for contexts/storage "
                              "(default: a tempdir, removed after)")
    loadgen.add_argument("--poll-interval", type=float, default=0.5,
                         metavar="SECONDS",
                         help="/healthz + /builds sampling interval")
    loadgen.add_argument("--ready-timeout", type=float, default=30.0,
                         metavar="SECONDS",
                         help="how long to wait for the worker's "
                              "/ready")
    loadgen.add_argument("--fleet", action="store_true",
                         help="fleet mode: spawn --workers in-process "
                              "workers behind the front-door "
                              "scheduler (plus a shared cache KV and "
                              "a single-worker baseline), drive "
                              "repeated same-context builds through "
                              "it, and report per-worker build "
                              "distribution, affinity hit-rate, "
                              "p99-vs-single-worker delta, drain-"
                              "driven peer chunk exchange, and a "
                              "mid-run worker kill's failover")
    loadgen.add_argument("--workers", type=int, default=3,
                         metavar="N",
                         help="fleet mode: in-process workers behind "
                              "the scheduler")
    loadgen.add_argument("--tenant-quota", type=int, default=1,
                         metavar="N",
                         help="fleet mode: per-tenant in-flight "
                              "quota at the front door (0 disables "
                              "the quota-enforcement phase)")
    loadgen.add_argument("--rounds", type=int, default=0,
                         metavar="R",
                         help="fleet mode: builds per context "
                              "(default 3; >= 3 so the warmup, "
                              "drain, and kill phases each get a "
                              "round)")
    loadgen.add_argument("--slo-smoke", action="store_true",
                         help="SLO fault-injection scenario: a "
                              "3-worker fleet with fast canary/"
                              "evaluation intervals, one worker "
                              "wedged via a held admission slot; "
                              "asserts the latency burn-rate alert "
                              "fires, routing shifts away "
                              "(health_demoted in the route ledger), "
                              "canary digests stay identical on "
                              "healthy workers, and the alert "
                              "resolves after the fault clears")
    loadgen.add_argument("--alert-events-out", default="",
                         metavar="FILE",
                         help="slo-smoke: write the alert transitions "
                              "(fired/resolved) as an alert-only "
                              "NDJSON file — the CI artifact")
    loadgen.add_argument("--evict-soak", action="store_true",
                         help="eviction soak scenario: the same "
                              "edited-rebuild stream against a "
                              "tiny-budget storage and an unbudgeted "
                              "oracle; asserts evictions fire, disk "
                              "high-water reaches steady state, "
                              "every round's digests match the "
                              "oracle byte for byte, and the "
                              "post-soak scrub finds zero corruption")
    loadgen.add_argument("--prewarm-smoke", action="store_true",
                         help="session-snapshot recovery scenario: a "
                              "worker is killed (no teardown) after a "
                              "resident warm build and a fresh worker "
                              "over the same storage must rebuild "
                              "warm_mode=restored, byte-identical, "
                              "within 2x of the resident floor; then "
                              "a 2-worker fleet drains a session "
                              "holder and the next build must land "
                              "on the prewarmed survivor")

    history = sub.add_parser(
        "history", help="render build-history trends, or `history "
                        "diff A B` to gate on regressions")
    history.add_argument("history_args", nargs="+",
                         metavar="PATH | diff A B",
                         help="history JSONL file(s) or directory "
                              "(rendered as a trend); or `diff A B` "
                              "to compare candidate B against "
                              "baseline A (exit 1 on a flagged "
                              "regression)")
    history.add_argument("--threshold", type=float, default=0.25,
                         metavar="FRACTION",
                         help="diff regression threshold: flag p50/"
                              "p99 latency growth or hit/dedup-ratio "
                              "drops beyond this fraction "
                              "(default 0.25)")
    history.add_argument("--limit", type=int, default=20,
                         help="records shown in the trend view")

    report = sub.add_parser(
        "report", help="critical-path analysis of a telemetry report")
    report.add_argument("metrics_file",
                        help="a --metrics-out JSON report OR a "
                             "diagnostic bundle (--diag-out) to "
                             "analyze; with --fleet, a merged events "
                             "JSONL (the fleet front door's "
                             "--events-out) instead")
    report.add_argument("--events", default="", metavar="FILE",
                        help="an --events-out JSONL log to include "
                             "(torn final lines of killed builds are "
                             "salvaged)")
    report.add_argument("--fleet", action="store_true",
                        help="cross-process fleet analysis: treat the "
                             "input as a merged event log (front-door "
                             "spans + teed worker events), assemble "
                             "one span tree per trace id across "
                             "processes, and render the cross-process "
                             "critical path (front-door quota wait vs "
                             "worker queue wait vs build phases, "
                             "failover attempts as sibling subtrees); "
                             "the top-level --trace-out writes the "
                             "merged Perfetto export")
    report.add_argument("--profile", default="", metavar="FILE",
                        help="with --fleet: a makisu-tpu.profile.v1 "
                             "artifact (e.g. `profile --fleet --out`) "
                             "to render beside the span analysis — "
                             "the sampled where-did-the-cycles-go "
                             "view next to the declared one")

    explain = sub.add_parser(
        "explain", help="chunk-level cache miss attribution from a "
                        "build's decision ledger")
    explain.add_argument("ledger",
                         help="an --explain-out JSONL ledger (an "
                              "--events-out log containing "
                              "cache_decision events also works)")
    explain.add_argument("--baseline", default="", metavar="LEDGER",
                         help="a previous build's ledger: render the "
                              "build-to-build diff (keys that flipped "
                              "hit→miss, file-level blame, re-chunked "
                              "byte delta) instead of single-build "
                              "attribution")
    explain.add_argument("--metrics", default="", metavar="FILE",
                         help="the matching --metrics-out report: adds "
                              "the warm-rebuild floor profile "
                              "(irreducible vs cache-avoidable wall "
                              "time per phase)")

    check = sub.add_parser(
        "check", help="repo-invariant static analysis: the six rules "
                      "distilled from shipped bugs (see "
                      "docs/ANALYSIS.md); exits 1 on any finding not "
                      "in the committed baseline")
    check.add_argument("paths", nargs="*", metavar="PATH",
                       help="files/directories to scan (default: the "
                            "makisu_tpu package)")
    check.add_argument("--json", action="store_true", dest="json_out",
                       help="machine-readable output: one JSON object "
                            "with findings/suppressed/baseline (the CI "
                            "gate's artifact)")
    check.add_argument("--update-baseline", action="store_true",
                       help="rewrite the baseline to the current "
                            "finding set (review the diff!) and exit 0")
    check.add_argument("--baseline", default="", metavar="FILE",
                       help="baseline file (default: the committed "
                            "makisu_tpu/analysis/baseline.json)")
    check.add_argument("--rule", action="append", default=[],
                       metavar="NAME",
                       help="run only this rule (repeatable)")

    doctor = sub.add_parser(
        "doctor", help="diagnose a failure-forensics bundle, or the "
                       "device route (--device)")
    doctor.add_argument("bundle", nargs="?", default="",
                        help="a diagnostic bundle JSON (written by "
                             "--diag-out, the stall watchdog, or the "
                             "SIGTERM/SIGUSR1 handlers); with "
                             "--device, the deviceprobe ledger file "
                             "or sessions directory instead")
    doctor.add_argument("--device", action="store_true",
                        help="cross-session device-route diagnosis "
                             "from the makisu-tpu.deviceprobe.v1 "
                             "ledger: dominant wedge phase/frame, "
                             "per-attachment verdict history, last "
                             "healthy window (default ledger: "
                             "$MAKISU_TPU_DEVICE_SESSIONS_DIR or "
                             "benchmarks/device_sessions)")
    doctor.add_argument("--fleet", action="store_true",
                        help="cross-worker fleet diagnosis: poll the "
                             "front door's /healthz at the given "
                             "SOCKET and name dead/draining workers, "
                             "stale peer-map acks, tenants pinned at "
                             "their quota, and placement-memo drift "
                             "vs actual session residency")
    doctor.add_argument("--storage", action="store_true",
                        help="storage-plane diagnosis: census + "
                             "reference audit + integrity scrub of "
                             "the four content planes (blob CAS, "
                             "chunk CAS, packs, recipes). TARGET is "
                             "a worker control socket (remote "
                             "report) or a storage dir (local walk; "
                             "default: the standard storage dir). "
                             "Exit 1 when findings exist")
    doctor.add_argument("--repair", action="store_true",
                        help="with --storage on a DIRECTORY target: "
                             "delete verified-orphaned zpack twins "
                             "(without this flag the repair is a "
                             "dry-run listing)")
    doctor.add_argument("--eviction-budget", type=int, default=None,
                        metavar="BYTES",
                        help="with --storage: publish an eviction "
                             "dry-run — what LRU eviction down to "
                             "this byte budget would remove and how "
                             "many bytes it would free (refused "
                             "while the chunk CAS LRU seed is "
                             "incomplete)")

    du = sub.add_parser(
        "du", help="storage census: per-plane object counts, byte "
                   "totals, age histogram, per-tenant attribution")
    du.add_argument("--storage", default="",
                    help="storage directory (default: the standard "
                         "storage dir)")
    du.add_argument("--json", action="store_true", dest="json_out",
                    help="machine-readable census document "
                         "(makisu-tpu.census.v1)")

    profile = sub.add_parser(
        "profile", help="render, capture, diff, and aggregate "
                        "wall-clock sampling profiles "
                        "(makisu-tpu.profile.v1)")
    profile.add_argument("target", nargs="*", default=[],
                         help="a profile artifact to render; "
                              "`diff BASELINE CANDIDATE` to attribute "
                              "a regression to the frames whose "
                              "self-time share grew; with --fleet, the "
                              "front door socket/address to capture "
                              "a merged cross-worker profile from")
    profile.add_argument("--top", type=int, default=10,
                         help="functions to list per table (default 10)")
    profile.add_argument("--threshold", type=float, default=0.1,
                         metavar="FRACTION",
                         help="diff: flag frames whose self-time share "
                              "grew by more than this fraction of "
                              "total samples (default 0.1 = ten "
                              "share points); exit 1 when any do")
    profile.add_argument("--flame", default="", metavar="FILE",
                         help="also write a self-contained flamegraph "
                              "HTML (phase-colored icicle) to FILE")
    profile.add_argument("--fleet", action="store_true",
                         help="TARGET is a fleet front door: ask every "
                              "alive worker for an on-demand "
                              "--seconds capture window and render "
                              "the merged profile")
    profile.add_argument("--seconds", type=float, default=5.0,
                         help="capture window for --fleet (default 5)")
    profile.add_argument("--out", default="", metavar="FILE",
                         help="also write the (merged) profile "
                              "artifact to FILE")

    sub.add_parser("version", help="print the build version")
    return parser


def _storage_dir(flag: str) -> str:
    if flag:
        return flag
    if os.path.isdir(os.path.dirname(pathutils.DEFAULT_STORAGE_DIR) or "/") \
            and os.access("/", os.W_OK):
        return pathutils.DEFAULT_STORAGE_DIR
    return os.path.join(os.path.expanduser("~"), ".makisu-tpu-storage")


def _parse_build_args(pairs: list[str]) -> dict[str, str]:
    out = {}
    for pair in pairs:
        key, sep, val = pair.partition("=")
        if not sep:
            val = os.environ.get(key, "")
        out[key] = val
    return out


def _new_cache_manager(args, store, registry_client=None):
    from makisu_tpu.cache import CacheManager, FSStore, HTTPStore, RedisStore
    from makisu_tpu.dockerfile import parse_duration
    ttl = parse_duration(args.local_cache_ttl) / 1e9
    if args.redis_cache_addr:
        kv = RedisStore(args.redis_cache_addr, ttl,
                        args.redis_cache_password)
    elif args.http_cache_addr:
        headers = dict(h.split(":", 1) for h in args.http_cache_header)
        kv = HTTPStore(args.http_cache_addr, headers)
    elif args.local_cache_ttl in ("0", "0s"):
        return None
    else:
        kv = FSStore(os.path.join(store.root,
                                  pathutils.CACHE_KV_FILE_NAME), ttl)
    return CacheManager(kv, store, registry_client=registry_client)


def cmd_build(args) -> int:
    from makisu_tpu.storage import contentstore
    storage_dir = _storage_dir(args.storage)
    if getattr(args, "storage_budget", None) is not None:
        # Per-dir override, not a process-global: a worker runs many
        # builds against many dirs, and one build's flag must not
        # rebudget its neighbors.
        contentstore.set_budget_for(storage_dir,
                                    max(0, args.storage_budget) << 20)
    if getattr(args, "storage_remote", None) is not None:
        contentstore.configure(remote=args.storage_remote)
    if getattr(args, "watch", False):
        if invocation_mode.get() == "worker":
            # A worker build runs on a handler thread; an endless
            # watch loop would pin it (and its session lease) forever.
            # The worker process is already resident — repeat
            # submissions get warm rebuilds without watching.
            log.warning("--watch is ignored in worker mode (the "
                        "worker itself is the resident process)")
        else:
            return _watch_loop(args)
    code = _build_once(args)
    # Enforce the byte budget at the moment disk grew (throttled;
    # no-op unbudgeted; never fails a finished build).
    contentstore.store_for(storage_dir).maybe_evict()
    return code


def _watch_loop(args) -> int:
    """``build --watch``: build, then stay resident and rebuild on
    every context change. Change detection rides the build session's
    dirty tracker (inotify when available); without a session (
    MAKISU_TPU_SESSION=0) a standalone mtime-walk snapshot polls. A
    failed rebuild keeps watching — the next edit gets its chance."""
    import importlib
    import time as time_mod

    from makisu_tpu.worker import session as session_mod
    walk_mod = importlib.import_module("makisu_tpu.snapshot.walk")

    interval = max(0.1, getattr(args, "watch_interval", 1.0))
    context_dir = os.path.abspath(args.context)
    # The standalone (session-less) poll must ignore the build's own
    # output dirs — a storage/root nested inside the context would
    # otherwise re-trigger a rebuild forever.
    poll_blacklist = [os.path.abspath(_storage_dir(args.storage)),
                      os.path.abspath(args.root)]

    def safe_build() -> int:
        """One rebuild that can never unwind the loop: a momentarily
        broken Dockerfile or a half-renamed COPY source is the normal
        rhythm of watch-mode editing — report, keep watching."""
        try:
            return _build_once(args)
        except KeyboardInterrupt:
            raise
        except SystemExit as e:
            log.error("watch: build exited: %s", e.code)
            return e.code if isinstance(e.code, int) else 1
        except Exception as e:  # noqa: BLE001 - watch must survive
            log.error("watch: build failed: %s", e)
            return 1

    code = safe_build()
    builds = 1
    snapshot = None
    log.info("watch: initial build exited %d; watching %s "
             "(interval %.1fs, Ctrl-C to exit)", code, context_dir,
             interval)
    try:
        while True:
            session = session_mod.manager().peek(context_dir)
            if session is not None:
                dirt = session.poll_changes()
            else:
                try:
                    if snapshot is None:
                        snapshot = walk_mod.snapshot_tree(
                            context_dir, poll_blacklist)
                        dirt = set()
                    else:
                        snapshot, delta = walk_mod.snapshot_delta(
                            snapshot, poll_blacklist)
                        dirt = delta.real_dirty
                except OSError:
                    # Context churned mid-walk (or vanished briefly):
                    # re-baseline next tick instead of dying.
                    snapshot = None
                    dirt = set()
            if dirt:
                sample = sorted(dirt)[:3]
                log.info("watch: %d paths changed (%s); rebuilding",
                         len(dirt), ", ".join(
                             os.path.relpath(p, context_dir)
                             for p in sample))
                code = safe_build()
                builds += 1
                log.info("watch: rebuild #%d exited %d", builds, code)
                snapshot = None  # re-baseline the standalone poll
            else:
                time_mod.sleep(interval)
    except KeyboardInterrupt:
        # A terminal Ctrl-C is delivered to the whole process group —
        # a second interrupt may land mid-log; exit quietly either way.
        try:
            log.info("watch: stopped after %d builds", builds)
        except KeyboardInterrupt:
            pass
        return code


def _build_once(args) -> int:
    from makisu_tpu.builder import BuildPlan
    from makisu_tpu.cache import NoopCacheManager
    from makisu_tpu.chunker import get_hasher
    from makisu_tpu.context import BuildContext
    from makisu_tpu.docker.image import ImageName
    from makisu_tpu.dockerfile import parse_file
    from makisu_tpu.registry import load_config_map, new_client
    from makisu_tpu.storage import ImageStore

    # Per-build registry config (never the process-global map: builds in
    # one worker may carry different --registry-config flags).
    registry_config_map = (load_config_map(args.registry_config)
                          if args.registry_config else None)
    # Validated per-build compression identity: threaded through the
    # BuildContext rather than tario's process globals, so concurrent
    # builds in one worker can use different flags. `auto` resolves to
    # a concrete backend HERE (logged once per build) — only concrete
    # backends enter cache identity.
    gzip_backend = tario.resolve_backend(args.gzip_backend)
    if args.gzip_backend == "auto":
        log.info("gzip backend auto-selected: %s", gzip_backend)
    gzip_backend_id = tario.make_backend_id(gzip_backend,
                                            args.compression)
    blacklist = list(pathutils.DEFAULT_BLACKLIST)
    for extra in args.blacklist:
        if extra not in blacklist:
            blacklist.append(extra)

    dockerfile_path = args.file or os.path.join(args.context, "Dockerfile")
    with open(dockerfile_path) as f:
        stages = parse_file(f.read(), _parse_build_args(args.build_arg))

    target = ImageName.parse(args.tag)
    replicas = [ImageName.parse(r) for r in args.replica]

    with ImageStore(_storage_dir(args.storage)) as store:
        ctx = BuildContext(args.root, os.path.abspath(args.context), store,
                           hasher=get_hasher(args.hasher),
                           blacklist=blacklist,
                           gzip_backend_id=gzip_backend_id)
        # The first push registry doubles as the cache's blob/chunk
        # transfer plane (the reference's registryCacheManager pulls
        # cached layers through the push registry the same way,
        # lib/cache/cache_manager.go:116-182): a KV hit from another
        # builder is materializable from there — lazily, and at chunk
        # granularity when the TPU hasher indexed the layer.
        cache_registry = None
        if args.push:
            cache_registry = new_client(
                store, target.with_registry(args.push[0]),
                config_map=registry_config_map)
        cache_mgr = (_new_cache_manager(args, store, cache_registry)
                     or NoopCacheManager())
        if args.hasher == "tpu" and not isinstance(cache_mgr,
                                                   NoopCacheManager):
            from makisu_tpu.cache.chunks import attach_chunk_dedup
            attach_chunk_dedup(cache_mgr, os.path.join(store.root, "chunks"))
        preserver = None
        if args.preserve_root and args.modifyfs:
            from makisu_tpu.storage.root_preserver import RootPreserver
            preserver = RootPreserver(args.root, store.sandbox_dir,
                                      ctx.blacklist)
        # Resident build session: lease (or mint) the warm state for
        # this context + resolved-flag identity. A reused session arms
        # the context with the dirty set, the scan memo, and the
        # resident statcache/layer state; every outcome lands on the
        # decision ledger (source=session) and the warm_mode history
        # label. Leased IMMEDIATELY before the try whose finally
        # releases it — any fallible setup between acquire and release
        # would leak the session busy forever.
        from makisu_tpu.utils import ledger as ledger_mod
        from makisu_tpu.worker import session as session_mod
        build_session = None
        abs_context = os.path.abspath(args.context)
        if session_mod.enabled():
            # The restore spec (storage dir + PORTABLE flag identity)
            # lets a cold acquire consult the chunk-addressed snapshot
            # plane: same logical build, any worker — the fleet front
            # door rewrites --storage per worker, which is exactly why
            # the portable identity excludes it.
            build_session, verdict = session_mod.manager().acquire(
                abs_context, session_mod.identity_from_build_args(
                    args, _storage_dir(args.storage), gzip_backend_id),
                restore_spec=(
                    _storage_dir(args.storage),
                    session_mod.portable_identity_from_build_args(
                        args, gzip_backend_id)))
        else:
            verdict = "disabled"
        build_ok = False
        try:
            if build_session is not None:
                mode = build_session.begin_build(
                    ctx,
                    resident_process=(
                        invocation_mode.get() == "worker"
                        or bool(getattr(args, "watch", False))))
                session_mod.set_warm_mode(
                    mode if verdict in ("hit", "restored")
                    else "fresh")
                ledger_mod.record(
                    "session", abs_context, verdict,
                    reason=("reused" if verdict == "hit"
                            else "restored" if verdict == "restored"
                            else "created"),
                    mode=mode, dirty=len(ctx.dirty_paths),
                    resident_bytes=build_session.resident_bytes())
            else:
                session_mod.set_warm_mode("off")
                ledger_mod.record("session", abs_context, "miss",
                                  reason=verdict)
            plan = BuildPlan(ctx, target, replicas, cache_mgr, stages,
                             allow_modify_fs=args.modifyfs,
                             force_commit=(args.commit == "implicit"),
                             stage_target=args.target,
                             registry_client=_FromPuller(
                                 store, registry_config_map))
            manifest = plan.execute()
            build_ok = True
        finally:
            if preserver is not None:
                preserver.restore()
            if build_session is not None:
                # A failed build de-certifies the dirty set (the next
                # build re-scans); a successful one re-arms the
                # watcher/snapshot so the next rebuild is O(dirty).
                build_session.finish_build(ctx, build_ok)
                session_mod.manager().release(build_session)
        log.info("successfully built image %s", target)

        # Lazily-pulled cache hits hold no local blob; pushes
        # materialize per-blob only when the target registry can't
        # HEAD-skip (the materialize_blob hook), export paths need every
        # byte (materialize_pending below).
        materializer = getattr(cache_mgr, "materialize", None)
        push_jobs = [(image, registry)
                     for registry in args.push
                     for image in (target, *replicas)]

        def push_one(job):
            image, registry = job
            name = image.with_registry(registry)
            client = new_client(store, name,
                                config_map=registry_config_map)
            client.materialize_blob = materializer
            client.push(name if name.registry else image)
            log.info("successfully pushed %s to %s", name, registry)

        if len(push_jobs) == 1:
            push_one(push_jobs[0])
        elif push_jobs:
            # Image-level fan-out across registries/replicas runs on
            # its own small pool; the blob transfers inside each push
            # share the transfer engine's global concurrency and
            # memory budget (a dedicated outer pool keeps the engine's
            # blob tasks leaves — the tier rule in registry/transfer).
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(min(4, len(push_jobs))) as pool:
                concurrency.ctx_map(pool, push_one, push_jobs)
        if args.dest or args.oci_dest or args.load:
            cache_mgr.materialize_pending()
        if args.dest:
            from makisu_tpu.docker.save import write_save_tar
            write_save_tar(store, target, args.dest)
            log.info("saved image tar to %s", args.dest)
        if args.oci_dest:
            from makisu_tpu.docker.oci import write_oci_layout
            digest = write_oci_layout(store, target, args.oci_dest)
            log.info("saved OCI layout to %s (manifest %s)",
                     args.oci_dest, digest)
        if args.load:
            from makisu_tpu.docker.daemon import DockerClient
            from makisu_tpu.docker.save import write_save_tar
            tar_path = os.path.join(store.sandbox_dir, "load.tar")
            write_save_tar(store, target, tar_path)
            DockerClient(args.docker_host,
                         args.docker_version).image_tar_load(tar_path)
            log.info("loaded image into docker daemon")
    log.info("finished building %s", target)
    return 0


class _FromPuller:
    """Registry access for FROM steps: resolves a client per image name
    and saves manifests under the image's own name."""

    def __init__(self, store, config_map=None) -> None:
        self.store = store
        self.config_map = config_map

    def pull(self, name):
        from makisu_tpu.registry import new_client
        return new_client(self.store, name,
                          config_map=self.config_map).pull(name)

    def start_pull(self, name):
        """Pipelined variant: FROM layer downloads run ahead on the
        transfer engine while extraction applies them in order."""
        from makisu_tpu.registry import new_client
        return new_client(self.store, name,
                          config_map=self.config_map).start_pull(name)


def cmd_pull(args) -> int:
    from makisu_tpu.docker.image import ImageName
    from makisu_tpu.registry import load_config_map, new_client
    from makisu_tpu.storage import ImageStore

    # Per-command map, not update_global_config: the worker serves
    # pull/push/diff concurrently with builds, and mutating the
    # process-global map would race other requests' config_for lookups.
    config_map = (load_config_map(args.registry_config)
                  if args.registry_config else None)
    name = ImageName.parse_for_pull(args.image)
    with ImageStore(_storage_dir(args.storage)) as store:
        if args.delta:
            from makisu_tpu.serve import pull_image_delta
            client = new_client(store, name, config_map=config_map)
            manifest, report = pull_image_delta(client, store, name,
                                                args.delta)
        else:
            client = new_client(store, name, config_map=config_map)
            # Snapshot which layers are already local BEFORE the pull:
            # pull_layer no-ops on present blobs, and the report must
            # say so (route "local", zero wire bytes) the same way the
            # delta report does for the same warm store. The snapshot
            # costs an extra manifest GET, so it only runs when a
            # report was actually asked for.
            local: set[str] = set()
            if args.report_out:
                pre = client.pull_manifest(name.tag)
                local = {d.digest.hex() for d in pre.layers
                         if store.layers.exists(d.digest.hex())}
            manifest = client.pull(name)
            if args.report_out:
                # Shared builder with the delta report, so a consumer
                # pointed at either file reads one shape. Repeated
                # digests dedup exactly like pull_image_delta's walk,
                # so the two reports agree on layer count and
                # denominator for the same image.
                from makisu_tpu.serve.client import build_pull_report
                uniq: dict[str, int] = {}
                for d in manifest.layers:
                    uniq.setdefault(d.digest.hex(), d.size)
                report = build_pull_report(name, "", [
                    {"layer": hx,
                     "route": "local" if hx in local else "blob",
                     "size": size,
                     "bytes_fetched": 0 if hx in local else size}
                    for hx, size in uniq.items()])
        if args.report_out:
            from makisu_tpu.utils import fileio
            fileio.write_json_atomic(args.report_out, report)
        log.info("pulled %s (%d layers)", name, len(manifest.layers))
        if args.oci_dest:
            from makisu_tpu.docker.oci import write_oci_layout
            digest = write_oci_layout(store, name, args.oci_dest)
            log.info("saved OCI layout to %s (manifest %s)",
                     args.oci_dest, digest)
        if args.extract:
            from makisu_tpu.snapshot import MemFS
            os.makedirs(args.extract, exist_ok=True)
            fs = MemFS(args.extract, blacklist=[])
            for desc in manifest.layers:
                fs.update_from_tar_path(store.layers.path(desc.digest.hex()),
                                        untar=True)
            log.info("extracted rootfs to %s", args.extract)
    return 0


def cmd_push(args) -> int:
    from makisu_tpu.docker.image import ImageName
    from makisu_tpu.docker.save import load_save_tar
    from makisu_tpu.registry import load_config_map, new_client
    from makisu_tpu.storage import ImageStore

    config_map = (load_config_map(args.registry_config)
                  if args.registry_config else None)
    name = ImageName.parse(args.tag)
    with ImageStore(_storage_dir(args.storage)) as store:
        load_save_tar(store, args.tar_path, name)
        registries = args.registries or [name.registry]
        if not all(registries):
            raise SystemExit("no registry to push to (use --push)")

        def push_to(registry):
            target = name.with_registry(registry)
            store.manifests.save(target, store.manifests.load(name))
            new_client(store, target, config_map=config_map).push(target)
            log.info("pushed %s", target)

        if len(registries) == 1:
            push_to(registries[0])
        else:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(min(4, len(registries))) as pool:
                concurrency.ctx_map(pool, push_to, registries)
    return 0


def cmd_diff(args) -> int:
    import tempfile

    from makisu_tpu.docker.image import ImageName
    from makisu_tpu.registry import load_config_map, new_client
    from makisu_tpu.snapshot import MemFS
    from makisu_tpu.storage import ImageStore

    config_map = (load_config_map(args.registry_config)
                  if args.registry_config else None)
    with ImageStore(_storage_dir(args.storage)) as store:
        trees = []
        configs = []
        for image in args.images:
            name = ImageName.parse_for_pull(image)
            manifest = new_client(store, name,
                                  config_map=config_map).pull(name)
            with store.layers.open(manifest.config.digest.hex()) as f:
                import json as json_mod

                configs.append(json_mod.load(f))
            root = tempfile.mkdtemp(dir=store.sandbox_dir)
            fs = MemFS(root, blacklist=[])
            for desc in manifest.layers:
                fs.update_from_tar_path(
                    store.layers.path(desc.digest.hex()), untar=False)
            trees.append(fs)
        # Whole-config deep diff (reference: cmd/diff.go:117-120 go-cmp's
        # the entire config object, so architecture/os/rootfs differences
        # surface, not just config.* fields).
        c1, c2 = configs
        for line in _deep_diff(c1, c2):
            print(line)
        diff = trees[0].compare(trees[1],
                                ignore_mtime=args.ignore_modtime)
        for p in diff.missing_in_first:
            print(f"only in {args.images[1]}: {p}")
        for p in diff.missing_in_second:
            print(f"only in {args.images[0]}: {p}")
        for p, h1, h2 in diff.different:
            print(f"differs: {p} "
                  f"[{h1.mode:o} {h1.uid}:{h1.gid} {h1.size}] vs "
                  f"[{h2.mode:o} {h2.uid}:{h2.gid} {h2.size}]")
    return 0


def _deep_diff(a, b, path: str = "") -> list[str]:
    """Recursive structural diff of two JSON-ish values, one line per
    differing leaf (analog of the reference's go-cmp report)."""
    if isinstance(a, dict) and isinstance(b, dict):
        lines = []
        for key in sorted(set(a) | set(b)):
            sub = f"{path}.{key}" if path else key
            if key not in a:
                lines.append(f"{sub}: <absent> != {b[key]!r}")
            elif key not in b:
                lines.append(f"{sub}: {a[key]!r} != <absent>")
            else:
                lines.extend(_deep_diff(a[key], b[key], sub))
        return lines
    if a != b:
        return [f"{path or '<root>'}: {a!r} != {b!r}"]
    return []


def cmd_report(args) -> int:
    """Critical-path analysis of a build's telemetry: where the wall
    time went, what to attack first. Input is a ``--metrics-out`` JSON
    report (and optionally the matching ``--events-out`` log) — or a
    diagnostic bundle from a build that died mid-flight, whose embedded
    metrics snapshot is analyzed instead: completed spans get phase
    self-times, open spans are marked with their age at capture."""
    import json as json_mod

    from makisu_tpu.utils import events as events_mod
    from makisu_tpu.utils import flightrecorder, traceexport

    if args.fleet:
        # Cross-process mode: the input is a merged event log — the
        # fleet front door's --events-out (its own spans + the teed
        # worker build events). Torn logs salvage like everywhere.
        try:
            event_log = events_mod.read_jsonl(args.metrics_file)
        except ValueError as e:
            log.warning("%s; analyzing the valid lines only", e)
            event_log = events_mod.read_jsonl(args.metrics_file,
                                              skip_invalid=True)
        assembled = traceexport.assemble_fleet_trace(event_log)
        if not assembled["traces"]:
            raise SystemExit(
                f"{args.metrics_file}: no span events to assemble "
                f"(expected a fleet --events-out log with "
                f"span_start/span_end lines)")
        fleet_profile = None
        if getattr(args, "profile", ""):
            from makisu_tpu.utils import profiler as profiler_mod
            try:
                fleet_profile = profiler_mod.read_artifact(args.profile)
            except ValueError as e:
                log.error("%s", e)
                raise SystemExit(2)
        print(traceexport.render_fleet_report(assembled,
                                              profile=fleet_profile),
              end="")
        if args.trace_out:
            metrics.write_json_atomic(
                args.trace_out,
                traceexport.fleet_perfetto_trace(assembled))
            log.info("merged fleet trace written to %s",
                     args.trace_out)
            # cli.main's generic trace write would clobber the merged
            # export with this report invocation's (empty) span tree.
            args.trace_out = ""
        return 0
    with open(args.metrics_file, encoding="utf-8") as f:
        report = json_mod.load(f)
    capture_ts = None
    if report.get("schema") == flightrecorder.BUNDLE_SCHEMA:
        bundle, report = report, report.get("metrics")
        capture_ts = bundle.get("ts")
        if report is None:
            raise SystemExit(
                f"{args.metrics_file}: bundle carries no metrics "
                f"snapshot (the dying process held the registry lock); "
                f"try `makisu-tpu doctor` for the thread/span forensics")
    if report.get("schema") != "makisu-tpu.metrics.v1":
        raise SystemExit(
            f"{args.metrics_file}: not a makisu-tpu metrics report "
            f"(schema {report.get('schema')!r})")
    event_log = None
    if args.events:
        try:
            event_log = events_mod.read_jsonl(args.events)
        except ValueError as e:
            # A build killed mid-write leaves one torn final line —
            # exactly the case a post-mortem report is FOR. Analyze
            # the valid prefix instead of dying.
            log.warning("%s; analyzing the valid lines only", e)
            event_log = events_mod.read_jsonl(args.events,
                                              skip_invalid=True)
    print(traceexport.render_report(report, event_log,
                                    capture_ts=capture_ts), end="")
    return 0


def cmd_explain(args) -> int:
    """Render a cache-decision ledger: which node broke the cache
    chain and which files broke it (default), what flipped between two
    builds (``--baseline``), and where the warm-rebuild floor actually
    goes (``--metrics``). Torn ledgers (build killed mid-write) are
    salvaged line-by-line, same as ``report --events``."""
    import json as json_mod

    from makisu_tpu.utils import explain as explain_mod
    from makisu_tpu.utils import ledger as ledger_mod

    def load(path: str) -> dict:
        try:
            led = ledger_mod.read_ledger(path)
        except ValueError as e:
            log.warning("%s; analyzing the valid lines only", e)
            led = ledger_mod.read_ledger(path, skip_invalid=True)
        if not led["decisions"] and not led["header"]:
            # Both inputs get this check: a wrong --baseline file
            # would otherwise render a misleading "0 flips" diff.
            raise SystemExit(
                f"{path}: no ledger header or cache_decision lines "
                f"(expected an --explain-out file, schema "
                f"{ledger_mod.LEDGER_SCHEMA!r})")
        return led

    current = load(args.ledger)
    if args.baseline:
        print(explain_mod.render_diff(current, load(args.baseline)),
              end="")
        return 0
    report = None
    if args.metrics:
        with open(args.metrics, encoding="utf-8") as f:
            report = json_mod.load(f)
        if report.get("schema") != "makisu-tpu.metrics.v1":
            raise SystemExit(
                f"{args.metrics}: not a makisu-tpu metrics report "
                f"(schema {report.get('schema')!r})")
    print(explain_mod.render_explain(current, report), end="")
    return 0


def cmd_du(args) -> int:
    """Walk the four content planes (blob CAS, chunk CAS, packs,
    recipes) under the census IO budget and print per-plane object
    counts, byte totals, the age histogram, and per-tenant
    attribution. ``--json`` emits the makisu-tpu.census.v1 document
    (also cached at ``<storage>/census.json`` for cheap reuse by
    /healthz and history records)."""
    import json as json_mod

    from makisu_tpu.cache import census as census_mod

    storage_dir = _storage_dir(args.storage)
    if not os.path.isdir(storage_dir):
        raise SystemExit(f"{storage_dir}: not a directory")
    doc = census_mod.StorageCensus(storage_dir).census()
    if args.json_out:
        print(json_mod.dumps(doc, indent=2, default=str))
    else:
        print(census_mod.render_du(doc), end="")
    return 0


def _doctor_storage(args) -> int:
    """``doctor --storage TARGET``: census + reference audit +
    integrity scrub. A socket target asks the worker for its cached
    report (the worker's own IO budget and scrub cadence apply); a
    directory target walks locally and can ``--repair`` orphaned
    zpack twins. Exit 1 when any finding survives."""
    import stat as stat_mod

    from makisu_tpu.cache import census as census_mod

    target = args.bundle
    is_socket = False
    if target:
        try:
            is_socket = stat_mod.S_ISSOCK(os.stat(target).st_mode)
        except OSError:
            is_socket = False
    if is_socket:
        if args.repair:
            raise SystemExit(
                "doctor --storage --repair needs a storage "
                "DIRECTORY target (repair deletes files; run it "
                "where the files are, not through a worker socket)")
        from makisu_tpu.worker import WorkerClient
        try:
            report = WorkerClient(target).storage(
                eviction_budget=args.eviction_budget)
        except (OSError, RuntimeError, ValueError) as e:
            raise SystemExit(
                f"worker on {target} not reachable: {e}")
        entries = list(report.get("storage") or [])
    else:
        storage_dir = _storage_dir(target)
        if not os.path.isdir(storage_dir):
            raise SystemExit(
                f"{storage_dir}: neither a worker socket nor a "
                f"storage directory")
        census = census_mod.StorageCensus(storage_dir)
        entry = {"storage_dir": storage_dir,
                 "census": census.census(),
                 "audit": census.audit(),
                 "scrub": census.scrub()}
        from makisu_tpu.storage import contentstore
        entry["contentstore"] = \
            contentstore.store_for(storage_dir).describe()
        seed = census_mod.seed_states(storage_dir)
        if seed:
            entry["lru_seed"] = seed
        if args.eviction_budget is not None:
            entry["eviction_dry_run"] = census.eviction_dry_run(
                args.eviction_budget, seed_state=seed)
        repairable = [f for f in entry["audit"]["findings"]
                      if f.get("repairable")]
        if repairable:
            entry["repair"] = census.repair_orphaned_zpacks(
                repairable, apply=args.repair)
        entries = [entry]
    print(census_mod.render_storage_doctor(
        entries, target or "local storage"), end="")
    total = sum(
        len((e.get("audit") or {}).get("findings") or [])
        + len((e.get("scrub") or {}).get("findings") or [])
        for e in entries)
    return 1 if total else 0


def cmd_doctor(args) -> int:
    """Render a diagnostic bundle into a human diagnosis: the stuck
    span, wedged threads, transfer-engine backlog, and the resource
    trajectory leading up to the capture. ``--device`` switches to the
    cross-session device-route diagnosis: every recorded backend-probe
    attempt (the ``makisu-tpu.deviceprobe.v1`` ledger), its verdict,
    the dominant wedge phase and sampled frame, and when the route was
    last healthy."""
    import json as json_mod

    from makisu_tpu.utils import flightrecorder

    if getattr(args, "storage", False):
        return _doctor_storage(args)
    if getattr(args, "fleet", False):
        from makisu_tpu.fleet import doctor as fleet_doctor
        from makisu_tpu.worker import WorkerClient
        if not args.bundle:
            raise SystemExit(
                "doctor --fleet needs the front door's socket path: "
                "`makisu-tpu doctor --fleet SOCKET`")
        client = WorkerClient(args.bundle)
        try:
            health = client.healthz()
        except (OSError, RuntimeError, ValueError) as e:
            raise SystemExit(
                f"fleet front door on {args.bundle} not reachable: "
                f"{e}")
        if "fleet" not in health:
            raise SystemExit(
                f"{args.bundle} answers /healthz but carries no "
                f"fleet section — is it a worker socket? point "
                f"doctor --fleet at the `makisu-tpu fleet` socket")
        # Active alerts render as findings (severity-ordered with the
        # rest of the diagnosis). Best-effort: a front door predating
        # /alerts still gets the healthz-digest fallback.
        alerts_snap = None
        try:
            alerts_snap = client.alerts()
        except (OSError, RuntimeError, ValueError):
            pass
        print(fleet_doctor.render_fleet_doctor(health, args.bundle,
                                               alerts=alerts_snap),
              end="")
        return 0
    if args.device:
        from makisu_tpu.utils import deviceprobe
        records = deviceprobe.read_records(args.bundle or None)
        if not records:
            where = (args.bundle or deviceprobe.sessions_dir()
                     or "$MAKISU_TPU_DEVICE_SESSIONS_DIR (unset)")
            raise SystemExit(
                f"no {deviceprobe.SCHEMA} records found in {where}; "
                f"probe attempts record there when a device is "
                f"configured (or when MAKISU_TPU_DEVICE_SESSIONS_DIR "
                f"is set explicitly)")
        print(deviceprobe.render_device_doctor(records), end="")
        return 0
    if not args.bundle:
        raise SystemExit(
            "doctor needs a diagnostic-bundle path (or --device for "
            "the device-route ledger diagnosis)")
    import stat as stat_mod
    if os.path.exists(args.bundle) and stat_mod.S_ISSOCK(
            os.stat(args.bundle).st_mode):
        # A live control socket instead of a bundle file: render the
        # process's active alerts as a diagnosis (works against a
        # worker or a fleet front door — the payload names itself).
        from makisu_tpu.fleet import doctor as fleet_doctor
        from makisu_tpu.utils import alerts as alerts_mod
        from makisu_tpu.worker import WorkerClient
        try:
            snap = WorkerClient(args.bundle).alerts()
        except (OSError, RuntimeError, ValueError) as e:
            raise SystemExit(
                f"{args.bundle} is a socket but /alerts failed: {e}")
        print(alerts_mod.render_alerts(
            snap, heading=f"{snap.get('source') or '?'} alerts — "
                          f"{args.bundle}"))
        findings = fleet_doctor.alert_findings(snap)
        if findings:
            print(f"\ndiagnosis ({len(findings)} finding(s)):")
            for f in findings:
                print(f"  [{f['severity']:<7s}] {f['detail']}")
        return 0
    with open(args.bundle, encoding="utf-8") as f:
        bundle = json_mod.load(f)
    if bundle.get("schema") != flightrecorder.BUNDLE_SCHEMA:
        raise SystemExit(
            f"{args.bundle}: not a makisu-tpu diagnostic bundle "
            f"(schema {bundle.get('schema')!r}); bundles are written "
            f"by --diag-out, the stall watchdog, or SIGTERM/SIGUSR1")
    print(flightrecorder.render_doctor(bundle), end="")
    return 0


def cmd_check(args) -> int:
    """Run the static-analysis rule engine over the tree: six rules
    distilled from shipped bugs (ctx propagation, signal safety,
    metric-name registry, atomic durable writes, silent swallows,
    unbounded I/O). Pre-existing findings live in the committed
    baseline; anything new exits 1 naming the rule, file, and line."""
    import json as json_mod

    from makisu_tpu import analysis

    rules = analysis.default_rules()
    if args.rule:
        wanted = set(args.rule)
        known = {r.name for r in rules}
        unknown = wanted - known
        if unknown:
            raise SystemExit(
                f"unknown rule(s) {', '.join(sorted(unknown))}; "
                f"valid: {', '.join(sorted(known))}")
        rules = [r for r in rules if r.name in wanted]
    paths = args.paths or analysis.default_scan_paths()
    root = analysis.repo_root()
    baseline_path = args.baseline or analysis.default_baseline_path()
    if args.update_baseline and not args.baseline \
            and (args.rule or args.paths):
        # write_baseline REPLACES the file with the current finding
        # set; updating the committed repo baseline from a filtered
        # scan would silently discard every other rule's/path's
        # entries. An explicit --baseline names a file the caller
        # owns, so partial scopes are fine there.
        raise SystemExit(
            "--update-baseline with --rule/PATH filters would drop "
            "every unscanned finding from the committed baseline; "
            "run it unfiltered, or pass an explicit --baseline FILE")
    findings = analysis.run_check(paths, rules, root=root)
    if args.update_baseline:
        analysis.write_baseline(baseline_path, findings)
        log.info("baseline updated: %d finding(s) recorded in %s",
                 len(findings), baseline_path)
        return 0
    baseline = analysis.load_baseline(baseline_path)
    new, suppressed = analysis.apply_baseline(findings, baseline)
    if args.json_out:
        print(json_mod.dumps({
            "schema": "makisu-tpu.check.v1",
            "findings": [f.to_dict() for f in new],
            "suppressed": suppressed,
            "baseline": os.path.relpath(baseline_path, root)
            if baseline_path.startswith(root) else baseline_path,
            "rules": sorted(r.name for r in rules),
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        print(f"makisu-tpu check: {len(new)} new finding(s), "
              f"{suppressed} baseline-suppressed")
    return 1 if new else 0


def cmd_worker(args) -> int:
    from makisu_tpu.utils import flightrecorder
    from makisu_tpu.utils import metrics as metrics_mod
    from makisu_tpu.worker import WorkerServer
    if args.storage_budget is not None or \
            args.storage_remote is not None:
        # Worker-wide defaults: every storage dir this worker builds
        # against inherits them (a build's own --storage-budget flag
        # still overrides per-dir).
        from makisu_tpu.storage import contentstore
        contentstore.configure(budget_mb=args.storage_budget,
                               remote=args.storage_remote)
    server = WorkerServer(args.socket,
                          stall_window=(args.stall_timeout or
                                        None),
                          diag_out=args.diag_out,
                          max_concurrent_builds=
                          args.max_concurrent_builds,
                          slo_config=args.slo_config,
                          alert_webhook=args.alert_webhook)
    # Process-level signal forensics: a worker killed by its
    # supervisor (SIGTERM) or poked for live inspection (SIGUSR1)
    # dumps a bundle covering EVERY in-flight build — the server's
    # process recorder sees all contexts' events via the global sink,
    # and the GLOBAL registry's trace id keeps every build's open
    # spans in the bundle. This replaces BOTH per-invocation handlers
    # cli.main installed, which would capture only the worker
    # invocation's own (empty) context.
    flightrecorder.install_signal_dumps(
        server.recorder, metrics_mod.global_registry(), args.diag_out)
    log.info("worker listening on %s", args.socket)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
    return 0


def cmd_serve(args) -> int:
    """Run the standalone distribution endpoint: read-only recipes +
    ranged pack serving over one storage directory a builder (or
    worker) populates. The CDN-edge shape of the serve plane — workers
    embed the same handlers on their own sockets."""
    from makisu_tpu.serve import ServeServer
    server = ServeServer(args.socket, _storage_dir(args.storage))
    stats = server.store.stats()
    log.info("serve endpoint on %s over %s (%d recipe(s), %d pack(s), "
             "%d pack bytes)", args.socket, server.storage_dir,
             stats["recipes"], stats["packs"], stats["pack_bytes"])
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
    return 0


def cmd_fleet(args) -> int:
    """Run the build-farm front door: a scheduler that fronts N
    workers, routing each build to the worker holding its resident
    session (affinity), placing new contexts by consistent hash with
    least-loaded spillover, enforcing per-tenant quotas, failing over
    past dead/refusing workers, and publishing the peer map workers
    use to fetch chunks from each other before the registry."""
    from makisu_tpu.fleet import FleetServer, WorkerSpec
    from makisu_tpu.utils import flightrecorder
    from makisu_tpu.utils import metrics as metrics_mod
    if not args.worker:
        raise SystemExit("fleet needs at least one "
                         "--worker SOCKET[=STORAGE]")
    specs = [WorkerSpec.parse(flag, i)
             for i, flag in enumerate(args.worker)]
    # The front door's own events — routing spans, decisions, teed
    # worker build events — happen on handler/poll threads that carry
    # NO bound context, so the --events-out/--explain-out sinks
    # cli.main bound in THIS context are promoted process-wide for the
    # server's lifetime. (Promotion replaces the old event_context
    # replay: one delivery path, no double-writes.)
    promoted = events.promote_context_sinks()
    server = FleetServer(
        args.socket, specs,
        poll_interval=args.poll_interval,
        tenant_quota=args.tenant_quota,
        max_inflight=args.max_inflight_builds,
        spillover_queue_depth=args.spillover_queue_depth,
        stall_window=(args.stall_timeout or None),
        diag_out=args.diag_out,
        slo_config=args.slo_config,
        alert_webhook=args.alert_webhook,
        canary_interval=args.canary_interval,
        canary_slow_seconds=args.canary_slow_seconds)
    # Process-level signal forensics, at parity with cmd_worker: a
    # SIGTERM'd front door dumps a bundle covering every in-flight
    # routed build (the server's recorder sees all contexts via the
    # global sink; the GLOBAL registry keeps every build's open route/
    # forward spans in it), and SIGUSR1 dumps one live WITHOUT
    # interrupting the in-flight builds.
    flightrecorder.install_signal_dumps(
        server.recorder, metrics_mod.global_registry(), args.diag_out)
    log.info("fleet front door listening on %s (%d workers: %s)",
             args.socket, len(specs),
             ", ".join(s.socket_path for s in specs))
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        # Pull every worker's serve access ledger BEFORE the sinks
        # demote: in a real multi-process fleet those rows (the
        # bytes-on-wire of peer/delta fetches, trace-id-stamped) live
        # only in the workers — delivering them here lands them in the
        # promoted --events-out file AND the merged-trace collector.
        # In-process fleets see them twice; the assembler dedupes.
        try:
            for access_event in server.collect_serve_access():
                events.deliver(access_event)
        except Exception as e:  # noqa: BLE001 - shutdown must proceed
            log.warning("serve-access collection failed: %s", e)
        trace_events = server.trace_events()
        server.server_close()
        events.demote_sinks(promoted)
        if args.trace_out:
            # The merged cross-process trace: the front door's own
            # spans plus every teed worker event, assembled per trace
            # id into one Perfetto export. Written here — and the flag
            # cleared — because cli.main's generic trace write only
            # sees the (empty) invocation registry, not the per-build
            # ones routing used.
            from makisu_tpu.utils import traceexport
            try:
                assembled = traceexport.assemble_fleet_trace(
                    trace_events)
                metrics.write_json_atomic(
                    args.trace_out,
                    traceexport.fleet_perfetto_trace(assembled))
                log.info("merged fleet trace written to %s "
                         "(%d trace(s), %d span(s))", args.trace_out,
                         len(assembled.get("traces", [])),
                         assembled.get("span_count", 0))
            except (OSError, ValueError) as e:
                log.error("failed to write merged fleet trace: %s", e)
            args.trace_out = ""
    return 0


def cmd_alerts(args) -> int:
    """Fetch and render ``GET /alerts`` from a worker or fleet front
    door: active alerts severity-first, the recently-resolved ring,
    and — on a fleet socket — each worker's own section."""
    import json as json_mod

    from makisu_tpu.utils import alerts as alerts_mod
    from makisu_tpu.worker import WorkerClient
    client = WorkerClient(args.socket)
    try:
        snap = client.alerts()
    except (OSError, RuntimeError, ValueError) as e:
        raise SystemExit(
            f"cannot fetch /alerts from {args.socket}: {e}")
    if args.json_out:
        print(json_mod.dumps(snap, indent=1))
        return 0
    source = snap.get("source") or "?"
    print(alerts_mod.render_alerts(
        snap, heading=f"{source} alerts — {args.socket}"))
    for wid, payload in sorted((snap.get("workers") or {}).items()):
        print()
        if payload.get("error"):
            print(f"worker {wid}: {payload['error']}")
        else:
            print(alerts_mod.render_alerts(
                payload, heading=f"worker {wid}"))
    canary = snap.get("canary") or {}
    if canary.get("workers"):
        print(f"\ncanary: {canary.get('sweeps', 0)} sweep(s), digest "
              f"mismatch={str(bool(canary.get('digest_mismatch'))).lower()}")
        for wid, row in sorted(canary["workers"].items()):
            print(f"  {wid}: score {row.get('score', 1.0):g} "
                  f"({row.get('bad', 0)}/{row.get('total', 0)} bad, "
                  f"last {row.get('latency_seconds', 0):g}s"
                  + (f", error: {row['error']}" if row.get("error")
                     else "") + ")")
    return 0


def cmd_sessions(args) -> int:
    """Resident-session surface of one worker: ``sessions SOCKET``
    lists the resident sessions plus the snapshot counters;
    ``sessions SOCKET snapshot [CONTEXT]`` checkpoints resident
    session state into the chunk-addressed snapshot plane;
    ``sessions SOCKET restore CONTEXT [--from SRC]`` stages a
    snapshot onto SOCKET (pulling the recipe from SRC when given —
    the fleet prewarm hand-off, driven by hand)."""
    import json as json_mod

    from makisu_tpu.worker import WorkerClient
    client = WorkerClient(args.socket)
    try:
        if args.verb == "snapshot":
            payload = client.snapshot_sessions(args.context)
            if args.json_out:
                print(json_mod.dumps(payload, indent=1))
            else:
                print(f"checkpointed {payload.get('snapshotted', 0)} "
                      f"session(s)")
            return 0
        if args.verb == "restore":
            if not args.context:
                raise SystemExit(
                    "sessions restore requires a context dir")
            if args.from_socket:
                recipe = WorkerClient(
                    args.from_socket).session_snapshot(args.context)
                payload = client.restore_session({"recipe": recipe})
            else:
                payload = client.restore_session(
                    {"context": args.context})
            if args.json_out:
                print(json_mod.dumps(payload, indent=1))
            elif payload.get("ok"):
                print("snapshot staged; the next build on this "
                      "context restores warm")
            else:
                print("restore refused: "
                      f"{payload.get('reason') or 'unknown'}")
            return 0 if payload.get("ok") else 1
        snap = client.sessions()
    except (OSError, RuntimeError, ValueError) as e:
        raise SystemExit(
            f"sessions {args.verb} via {args.socket} failed: {e}")
    if args.json_out:
        print(json_mod.dumps(snap, indent=1))
        return 0
    sessions = snap.get("sessions") or []
    print(f"{len(sessions)} resident session(s) — {args.socket}")
    for row in sessions:
        print(f"  {row.get('context', '?')}: builds={row.get('builds', 0)} "
              f"bytes={row.get('resident_bytes', 0)} "
              f"exact={str(bool(row.get('exact'))).lower()} "
              f"busy={str(bool(row.get('busy'))).lower()}")
    counters = snap.get("snapshot") or {}
    if counters:
        print("snapshot: " + " ".join(
            f"{k}={counters[k]}" for k in
            ("write", "write_error", "restore", "restore_refused",
             "restore_error") if k in counters))
        failure = counters.get("last_restore_failure") or {}
        if failure.get("reason"):
            print(f"  last restore failure: {failure.get('context', '?')} "
                  f"({failure['reason']})")
    return 0


def cmd_top(args) -> int:
    """Live terminal view of a worker: in-flight builds (tenant,
    phase, progress age, queue wait, cache hit rate), the admission
    queue, and the transfer plane — polled from ``/builds`` +
    ``/healthz``."""
    from makisu_tpu.tools import top
    return top.run(args)


def cmd_loadgen(args) -> int:
    """Synthetic concurrent-build load harness: N lanes of generated-
    context builds against a real worker, reporting p50/p99 latency,
    the queue-wait split, per-tenant fairness, hash-batch occupancy,
    and the cache hit-rate trajectory."""
    from makisu_tpu.tools import loadgen
    return loadgen.run(args)


def cmd_history(args) -> int:
    """Render build-history trends, or gate on a regression:
    ``history PATH...`` renders the trend view; ``history diff A B``
    compares candidate B against baseline A. Exit codes are gate-
    script friendly: 0 = ok, 1 = a latency/cache regression beyond
    ``--threshold`` was flagged, 2 = unreadable input (a missing
    baseline must not look like a regression)."""
    from makisu_tpu.utils import history as history_mod
    tokens = args.history_args

    def read(path: str) -> list[dict]:
        try:
            return history_mod.read_history(path)
        except OSError as e:
            log.error("cannot read history %s: %s", path, e)
            raise SystemExit(2)

    if tokens[0] == "diff":
        if len(tokens) != 3:
            raise SystemExit(
                "history diff takes exactly two history paths: "
                "`makisu-tpu history diff BASELINE CANDIDATE`")
        result = history_mod.diff(read(tokens[1]), read(tokens[2]),
                                  threshold=args.threshold)
        print(history_mod.render_diff(result), end="")
        return 0 if result["ok"] else 1
    records: list[dict] = []
    for path in tokens:
        records.extend(read(path))
    records.sort(key=lambda r: r.get("ts", 0.0))
    print(history_mod.render_trends(records, limit=args.limit),
          end="")
    return 0


def cmd_profile(args) -> int:
    """Work with wall-clock sampling profiles: ``profile ARTIFACT``
    renders the phase-attributed breakdown (``--flame`` adds a
    self-contained flamegraph HTML); ``profile diff BASELINE
    CANDIDATE`` attributes a regression to the frames whose self-time
    share grew; ``profile --fleet SOCKET`` captures and merges an
    on-demand window from every alive worker. Exit codes follow the
    ``history diff`` gate contract: 0 = ok, 1 = a frame regressed
    beyond ``--threshold``, 2 = unreadable input."""
    from makisu_tpu.utils import profiler as profiler_mod
    tokens = args.target

    def read(path: str) -> dict:
        try:
            return profiler_mod.read_artifact(path)
        except ValueError as e:
            log.error("%s", e)
            raise SystemExit(2)

    if args.fleet:
        from makisu_tpu.worker import WorkerClient
        if not tokens:
            raise SystemExit(
                "profile --fleet needs the front door's socket path: "
                "`makisu-tpu profile --fleet SOCKET`")
        client = WorkerClient(tokens[0],
                              control_timeout=args.seconds + 30.0)
        try:
            doc = client.profile(seconds=args.seconds)
        except (OSError, RuntimeError, ValueError) as e:
            raise SystemExit(
                f"fleet profile capture from {tokens[0]} failed: {e}")
    elif tokens and tokens[0] == "diff":
        if len(tokens) != 3:
            raise SystemExit(
                "profile diff takes exactly two artifacts: "
                "`makisu-tpu profile diff BASELINE CANDIDATE`")
        result = profiler_mod.diff(read(tokens[1]), read(tokens[2]),
                                   threshold=args.threshold)
        print(profiler_mod.render_diff(result), end="")
        return 0 if result["ok"] else 1
    elif len(tokens) == 1:
        doc = read(tokens[0])
    else:
        raise SystemExit(
            "profile takes one artifact path, `diff BASELINE "
            "CANDIDATE`, or `--fleet SOCKET`")
    print(profiler_mod.render_profile(doc, top=args.top), end="")
    if args.flame:
        try:
            with open(args.flame, "w", encoding="utf-8") as f:
                f.write(profiler_mod.flamegraph_html(doc))
            log.info("flamegraph written to %s", args.flame)
        except OSError as e:
            log.error("failed to write flamegraph: %s", e)
            return 1
    if args.out:
        try:
            profiler_mod.write_artifact(args.out, doc)
            log.info("profile artifact written to %s", args.out)
        except OSError as e:
            log.error("failed to write profile artifact: %s", e)
            return 1
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = make_parser()
    args = parser.parse_args(argv)
    log.configure(args.log_level.replace("warn", "warning"), args.log_fmt,
                  args.log_output)
    if args.transfer_concurrency or args.transfer_memory_budget:
        from makisu_tpu.registry import transfer
        transfer.configure(args.transfer_concurrency,
                           args.transfer_memory_budget)
    hash_workers_token = None
    if args.hash_workers > 0:
        # Context-scoped (like the metrics registry): concurrent
        # worker builds can carry different worker counts.
        hash_workers_token = concurrency.set_hash_workers(
            args.hash_workers)
    compress_workers_token = None
    if args.compress_workers > 0:
        compress_workers_token = concurrency.set_compress_workers(
            args.compress_workers)
    if args.hash_linger_ms >= 0:
        # Process-wide by design: the hash service batches ACROSS
        # builds, so there is one linger per process.
        concurrency.set_hash_linger_ms(args.hash_linger_ms)
    if args.command == "version":
        print(makisu_tpu.BUILD_HASH)
        return 0
    handlers = {"build": cmd_build, "pull": cmd_pull, "push": cmd_push,
                "diff": cmd_diff, "worker": cmd_worker,
                "serve": cmd_serve,
                "fleet": cmd_fleet, "report": cmd_report,
                "doctor": cmd_doctor, "explain": cmd_explain,
                "check": cmd_check, "top": cmd_top,
                "alerts": cmd_alerts, "sessions": cmd_sessions,
                "loadgen": cmd_loadgen, "history": cmd_history,
                "du": cmd_du, "profile": cmd_profile}
    handler = handlers.get(args.command)
    if handler is None:
        parser.print_help()
        return 1
    profiler = None
    if args.cpu_profile:
        profiler = cProfile.Profile()
        profiler.enable()
    jax_trace = False
    if getattr(args, "jax_profile", ""):
        # Importing ops FIRST re-asserts JAX_PLATFORMS from the env
        # (sitecustomize preloads jax pinned to the axon TPU tunnel;
        # start_trace would otherwise initialize that backend before
        # the build's own platform selection and can hang on it).
        from makisu_tpu import ops  # noqa: F401
        import jax
        jax.profiler.start_trace(args.jax_profile)
        jax_trace = True
    # Every invocation gets its own telemetry registry, bound to this
    # context exactly like the worker's per-build log sink: concurrent
    # builds in one worker never mix span trees or counters, while the
    # process-global registry (the worker's /metrics) still aggregates.
    registry = metrics.MetricsRegistry()
    # Trace adoption: when an upstream caller handed this invocation a
    # trace context (the worker binds the /build request's traceparent;
    # the fleet forwarder sends its forward span's), the fresh registry
    # JOINS that trace — same trace id, root span id = the caller's
    # span — so front door → worker → peer fetch all tell one causal
    # story. A malformed value mints fresh ids (counted, never fatal).
    metrics.adopt_inbound(registry, metrics.inbound_traceparent())
    metrics_token = metrics.set_build_registry(registry)
    # Alerts fired during this invocation's window: the SLO evaluator
    # (worker/fleet background thread) bumps the process-GLOBAL fired
    # counter, so the delta across this build is what the history
    # record carries — `history diff` attributes latency regressions
    # that coincide with alert storms.
    alerts_fired_base = metrics.global_registry().counter_total(
        metrics.ALERTS_FIRED)
    # Deploy-identity info gauge: constant 1, identity in the labels
    # (the node_exporter "build_info" idiom). Scrapers join it against
    # rate() series to slice by version/hasher/platform/mode.
    # native_isa: the runtime-dispatched SIMD route of the layer-commit
    # hot path (native.py), e.g. "gear=avx2,sha=shani" — resolved once
    # per process and NEVER part of cache identity (every route emits
    # identical bytes). Only CPU-backend builds force the native
    # library load (the only case the gear route engages); everything
    # else labels whatever is already resolved — an accelerator build
    # must not pay a synchronous `make -C native` for a telemetry
    # label.
    from makisu_tpu import native as _native
    metrics.gauge_set(
        metrics.BUILD_INFO, 1,
        version=makisu_tpu.__version__,
        command=args.command or "",
        hasher=getattr(args, "hasher", "") or "",
        platform=os.environ.get("JAX_PLATFORMS", "") or "default",
        mode=invocation_mode.get(),
        hash_workers=concurrency.hash_workers(),
        compress_workers=concurrency.compress_workers(),
        hash_linger_ms=concurrency.hash_linger_ms(),
        native_isa=(_native.isa_label()
                    if args.command == "build"
                    and os.environ.get("JAX_PLATFORMS", "") == "cpu"
                    else (_native.isa_route_if_resolved()
                          or "unresolved")))
    # Failure forensics: every invocation arms a flight recorder (a
    # lock-free ring of recent events/log records) and the process
    # resource sampler. Cost when nothing goes wrong: one deque append
    # per event. When something does — failure, stall, SIGTERM — the
    # recorder dumps a diagnostic bundle `makisu-tpu doctor` can read.
    from makisu_tpu.utils import flightrecorder, resources
    resources.ensure_started()
    # This invocation's own progress clock: every thread the build
    # spawns inherits the cell, so a per-build stall watchdog in a
    # busy worker watches THIS build, not its neighbors.
    progress_token = events.bind_progress_cell()
    recorder = flightrecorder.FlightRecorder()
    recorder_tokens = flightrecorder.install(recorder)
    # SIGTERM (the CI-timeout kill) dumps then unwinds; SIGUSR1 dumps
    # and keeps building. Worker mode replaces these with
    # process-level handlers (cmd_worker); in-worker builds run on
    # handler threads, where install_signal_dumps is a no-op.
    old_signal_handlers = flightrecorder.install_signal_dumps(
        recorder, registry, args.diag_out, tag=registry.trace_id[:8])
    # Continuous profiling: real-work commands run under the wall-clock
    # sampler. This invocation's thread is bound to its trace id so the
    # sampler attributes its stacks to THIS build even inside a busy
    # worker; a process-level sampler (the worker's, or loadgen's) is
    # reused rather than double-sampled — ownership decides who stops
    # it and clears the registry slot.
    from makisu_tpu.utils import profiler as profiler_mod
    sampler = None
    sampler_thread_token = None
    if args.command in ("build", "pull", "push", "diff", "loadgen"):
        sampler_thread_token = profiler_mod.bind_thread(
            registry.trace_id)
        if profiler_mod.process_profiler() is None:
            sample_hz = profiler_mod.resolve_hz(args.profile_hz)
            if sample_hz > 0:
                sampler = profiler_mod.SamplingProfiler(
                    hz=sample_hz).start()
                profiler_mod.set_process_profiler(sampler)
    events_writer = None
    events_token = None
    if args.events_out:
        try:
            events_writer = events.JsonlWriter(args.events_out)
            events_token = events.add_sink(events_writer)
        except OSError as e:
            log.error("failed to open events log %s: %s",
                      args.events_out, e)
    # The cache-decision ledger rides the same event bus: the writer is
    # just a sink filtering cache_decision events into the compact
    # --explain-out artifact (header + one line per consult + summary).
    ledger_writer = None
    ledger_token = None
    if args.explain_out:
        from makisu_tpu.utils import ledger as ledger_mod
        try:
            ledger_writer = ledger_mod.LedgerWriter(
                args.explain_out, trace_id=registry.trace_id,
                command=args.command or "")
            ledger_token = events.add_sink(ledger_writer)
        except OSError as e:
            log.error("failed to open cache ledger %s: %s",
                      args.explain_out, e)
    # The watchdog starts AFTER every event sink is bound: it runs
    # under a copy of this context, so its `stall` event reaches the
    # recorder, the --events-out log, and (in a worker) the client's
    # live stream. The `worker` command is exempt: a per-invocation
    # watchdog has no active_fn gate and would flag a healthy IDLE
    # worker as stalled — cmd_worker's server arms its own, gated on
    # in-flight builds. The `fleet` front door is exempt for the same
    # reason (long-lived, legitimately idle between submissions).
    watchdog = None
    stall_timeout = (args.stall_timeout or
                     flightrecorder.stall_timeout_from_env())
    if stall_timeout > 0 and args.command not in ("worker", "fleet",
                                                  "serve"):
        watchdog = flightrecorder.StallWatchdog(
            stall_timeout, recorder,
            flightrecorder.forced_bundle_path(
                args.diag_out, "stall", tag=registry.trace_id[:8]),
            registry, cell=events.progress_cell()).start()
    # argv deliberately stays out of the event record: it can carry
    # credentials (--redis-cache-password, registry configs).
    events.emit("build_start", trace_id=registry.trace_id,
                command=args.command or "",
                version=makisu_tpu.__version__)
    code = 1
    try:
        with metrics.span(args.command or "cli"):
            code = handler(args)
        return code
    except SystemExit as e:
        # A signal handler's SystemExit(143) or a subcommand's
        # SystemExit(msg) unwinds through here: record the true exit
        # code so build_end (and the failure-dump gate) see 143/1,
        # not the untouched sentinel.
        code = (e.code if isinstance(e.code, int)
                else 0 if e.code is None else 1)
        raise
    except Exception as e:  # noqa: BLE001 - top-level CLI boundary
        log.error("failed to execute command: %s", e)
        if args.log_level == "debug":
            raise
        return 1
    finally:
        events.emit("build_end", trace_id=registry.trace_id,
                    exit_code=code)
        if watchdog is not None:
            watchdog.stop()
        flightrecorder.restore_signal_handlers(old_signal_handlers)
        if (code != 0
                and args.command in ("build", "pull", "push", "diff")
                and not recorder.captured_terminal_moment()):
            # A stall/SIGTERM dump already froze the interesting
            # moment (a SIGUSR1 inspection poke doesn't count);
            # otherwise a plain failure dumps here (opt-in via
            # --diag-out / $MAKISU_TPU_DIAG_DIR — red CI runs upload
            # the bundle as an artifact). Only real-work commands
            # dump: a failed `report`/`doctor` analysis has no build
            # to do forensics ON, and the `worker` command's
            # forensics are the PROCESS-level handlers in cmd_worker
            # — this invocation-scoped recorder, blind to the builds,
            # would clobber the SIGTERM bundle they just wrote at the
            # same --diag-out path.
            diag_path = flightrecorder.resolve_bundle_path(
                args.diag_out, "failure", tag=registry.trace_id[:8])
            if diag_path:
                try:
                    recorder.dump(diag_path, "failure", registry,
                                  exit_code=code)
                    log.info("diagnostic bundle written to %s",
                             diag_path)
                except OSError as e:
                    log.error("failed to write diagnostic bundle: %s", e)
        elif recorder.last_dump_path:
            log.info("diagnostic bundle written to %s",
                     recorder.last_dump_path)
        if events_token is not None:
            events.reset_sink(events_token)
        if events_writer is not None:
            events_writer.close()
            log.info("event log written to %s", args.events_out)
        if ledger_token is not None:
            events.reset_sink(ledger_token)
        if ledger_writer is not None:
            # Closing AFTER the build_end emit above: the summary line
            # carries the exit code the writer captured from it.
            ledger_writer.close()
            log.info("cache ledger written to %s", args.explain_out)
        flightrecorder.uninstall(recorder_tokens)
        events.reset_progress_cell(progress_token)
        metrics.reset_build_registry(metrics_token)
        if hash_workers_token is not None:
            concurrency.reset_hash_workers(hash_workers_token)
        if compress_workers_token is not None:
            concurrency.reset_compress_workers(compress_workers_token)
        if jax_trace:
            import jax
            jax.profiler.stop_trace()
            log.info("jax profiler trace written to %s", args.jax_profile)
        if profiler is not None:
            profiler.disable()
            profiler.dump_stats("/tmp/makisu-tpu.prof")
            log.info("cpu profile written to /tmp/makisu-tpu.prof")
        if sampler_thread_token is not None:
            profiler_mod.unbind_thread(sampler_thread_token)
        if sampler is not None:
            # Stop BEFORE snapshotting so the artifact's duration is
            # the command's, not the teardown's.
            sampler.stop()
        active_sampler = sampler or profiler_mod.process_profiler()
        if args.profile_out:
            if active_sampler is not None:
                try:
                    profiler_mod.write_artifact(
                        args.profile_out, active_sampler.snapshot(
                            command=args.command or ""))
                    log.info("profile written to %s", args.profile_out)
                except OSError as e:
                    log.error("failed to write profile: %s", e)
            else:
                log.info("profile requested but the sampler is "
                         "disabled (--profile-hz 0 / "
                         "MAKISU_TPU_PROFILE_HZ=0)")
        if sampler is not None:
            profiler_mod.set_process_profiler(None)
        if args.command == "build":
            # One greppable line with the build's vital signs; the full
            # breakdown lives in --metrics-out / the worker's /metrics.
            log.info("build telemetry", exit_code=code,
                     **metrics.summary(registry))
        # Build-history record: one compact JSONL line per real-work
        # invocation, appended to --history-out (or
        # $MAKISU_TPU_HISTORY_DIR/history.jsonl) — the durable perf
        # trajectory `makisu-tpu history` renders and `history diff`
        # gates on. Only real-work commands record: a `report` or
        # `history` invocation has no build trajectory to extend.
        history_path = ""
        if args.command in ("build", "pull", "push"):
            from makisu_tpu.utils import history as history_mod
            history_path = history_mod.resolve_out(args.history_out)
        if args.metrics_out or args.trace_out or history_path:
            # One registry.report() feeds every output — the span tree
            # and counter tables are not walked twice per build.
            report = registry.report()
            report["command"] = args.command or ""
            report["exit_code"] = code
            if history_path:
                # Storage-plane snapshot beside the perf gates: the
                # CACHED census totals only (census.json written by
                # the last walk) — a history append must never pay a
                # multi-GB store walk.
                storage_bytes = None
                try:
                    from makisu_tpu.cache import census as census_mod
                    storage_bytes = census_mod.cached_totals(
                        _storage_dir(getattr(args, "storage", "")))
                except Exception as exc:  # noqa: BLE001 - telemetry
                    log.debug("history storage snapshot skipped: %s",
                              exc)
                    storage_bytes = None
                extra = ({"storage_bytes": storage_bytes}
                         if storage_bytes else {})
                extra["alerts_fired"] = int(
                    metrics.global_registry().counter_total(
                        metrics.ALERTS_FIRED) - alerts_fired_base)
                try:
                    history_mod.append_record(
                        history_path,
                        history_mod.record_from_report(
                            report, command=args.command or "",
                            exit_code=code, **extra))
                    log.info("history record appended to %s",
                             history_path)
                except OSError as e:
                    log.error("failed to append history record: %s",
                              e)
            if args.metrics_out:
                try:
                    metrics.write_json_atomic(args.metrics_out, report)
                    log.info("telemetry report written to %s",
                             args.metrics_out)
                except OSError as e:
                    log.error("failed to write telemetry report: %s", e)
            if args.trace_out:
                try:
                    from makisu_tpu.utils import traceexport
                    metrics.write_json_atomic(
                        args.trace_out,
                        traceexport.perfetto_trace(report))
                    log.info("perfetto trace written to %s",
                             args.trace_out)
                except OSError as e:
                    log.error("failed to write perfetto trace: %s", e)


if __name__ == "__main__":
    sys.exit(main())
