"""Worker client: submit builds to a long-lived worker.

Reference: lib/client/client.go (MakisuClient{Ready,Build,Exit}:36-61,
context copy into the shared mount prepareContext:141, log streaming +
build_code extraction readLines:160-191).
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import time

from makisu_tpu.utils import fileio
from makisu_tpu.utils import logging as log

# Transient transport failures a control-plane GET may retry: the
# socket vanished/refused (worker restarting), the connection died
# mid-exchange, or the worker sat past the timeout. Deliberately NOT
# retried: HTTP-level errors (the worker answered; retrying won't
# change its mind) and anything on POST /build (failover across
# workers is the scheduler's job, not the client's).
_TRANSIENT_ERRORS = (ConnectionError, FileNotFoundError, socket.timeout,
                     http.client.RemoteDisconnected,
                     http.client.NotConnected)


class _UnixHTTPConnection(http.client.HTTPConnection):
    """HTTP over a unix socket with a SEPARATE connect timeout: an
    unreachable worker (dead socket, full backlog) must fail the
    caller in ``connect_timeout`` seconds, while reads keep the long
    ``timeout`` a multi-minute build stream legitimately needs. The
    fleet scheduler's failover path depends on the former being
    prompt."""

    def __init__(self, path: str, timeout: float,
                 connect_timeout: float | None = None) -> None:
        super().__init__("localhost", timeout=timeout)
        self._path = path
        self._connect_timeout = (connect_timeout
                                 if connect_timeout is not None
                                 else timeout)

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self._connect_timeout)
        sock.connect(self._path)
        sock.settimeout(self.timeout)
        self.sock = sock


def iter_stream_lines(resp, chunk_size: int = 4096):
    """Complete NDJSON lines (bytes, blank lines skipped) from a
    streamed HTTP response — the ONE framing loop shared by
    ``WorkerClient.build`` and the fleet forwarder, so the /build wire
    format has a single parser. Stops at EOF; a truncated trailing
    fragment (no newline) is dropped — exactly the mid-stream-death
    signal both consumers read as "no terminal frame arrived"."""
    buf = b""
    while True:
        chunk = resp.read(chunk_size)
        if not chunk:
            return
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if line.strip():
                yield line


def terminal_exit_code(payload: dict) -> int:
    """Exit code from a /build terminal frame: ``exit_code`` (data)
    first, the stringly legacy ``build_code`` second."""
    code = payload.get("exit_code")
    try:
        return int(payload["build_code"]) if code is None \
            else int(code)
    except (KeyError, TypeError, ValueError):
        return 1


class PercentileStats(dict):
    """A ``metrics.percentile_stats`` digest with typed accessors.
    Still a dict (wire-format compatible); missing fields read as 0."""

    @property
    def count(self) -> int:
        return int(self.get("count", 0))

    @property
    def p50(self) -> float:
        return float(self.get("p50", 0.0))

    @property
    def p90(self) -> float:
        return float(self.get("p90", 0.0))

    @property
    def p99(self) -> float:
        return float(self.get("p99", 0.0))


class WorkerHealth(dict):
    """``GET /healthz`` payload with typed queue/latency fields —
    callers stop re-parsing raw dicts for the fields every dashboard
    needs. Still a plain dict underneath, so existing subscript
    consumers keep working unchanged."""

    @property
    def active_builds(self) -> int:
        return int(self.get("active_builds", 0))

    @property
    def builds_started(self) -> int:
        return int(self.get("builds_started", 0))

    @property
    def builds_succeeded(self) -> int:
        return int(self.get("builds_succeeded", 0))

    @property
    def builds_failed(self) -> int:
        return int(self.get("builds_failed", 0))

    @property
    def uptime_seconds(self) -> float:
        return float(self.get("uptime_seconds", 0.0))

    @property
    def queue_depth(self) -> int:
        return int(self.get("queue", {}).get("depth", 0))

    @property
    def max_concurrent_builds(self) -> int:
        return int(self.get("queue", {}).get(
            "max_concurrent_builds", 0))

    @property
    def queue_wait(self) -> PercentileStats:
        return PercentileStats(
            self.get("queue", {}).get("wait_seconds", {}))

    @property
    def build_latency(self) -> PercentileStats:
        return PercentileStats(
            self.get("queue", {}).get("latency_seconds", {}))

    @property
    def tenant_latency(self) -> dict[str, PercentileStats]:
        return {tenant: PercentileStats(stats)
                for tenant, stats in self.get("queue", {}).get(
                    "tenant_latency_seconds", {}).items()}

    @property
    def last_progress_seconds(self) -> float:
        return float(self.get("last_progress_seconds", 0.0))

    @property
    def transfer_inflight_bytes(self) -> int:
        return int(self.get("transfer_inflight_bytes", 0))

    @property
    def device(self) -> dict:
        """The ``device`` section: probe state + dispatch digests."""
        return dict(self.get("device", {}))

    @property
    def sessions(self) -> dict:
        """The ``sessions`` section: resident build-session digest
        (count, resident bytes vs budget, hits, invalidations)."""
        return dict(self.get("sessions", {}))

    @property
    def session_resident_bytes(self) -> int:
        return int(self.get("sessions", {}).get("resident_bytes", 0))

    @property
    def storage(self) -> dict:
        """The ``storage`` section: per-plane census digest, total
        bytes, the chunk CAS LRU seed state, and finding counts."""
        return dict(self.get("storage", {}))

    @property
    def storage_total_bytes(self) -> int:
        return int(self.get("storage", {}).get("total_bytes", 0))

    @property
    def device_probe_state(self) -> str:
        """Probe verdict: ok|pending|wedged|failed|absent|disabled."""
        return str(self.get("device", {}).get("probe", {})
                   .get("state", ""))


class BuildInfo(dict):
    """One row of ``GET /builds`` with typed accessors."""

    @property
    def id(self) -> int:
        return int(self.get("id", 0))

    @property
    def tenant(self) -> str:
        return str(self.get("tenant", ""))

    @property
    def state(self) -> str:
        return str(self.get("state", ""))

    @property
    def phase(self) -> str:
        return str(self.get("phase", ""))

    @property
    def trace_id(self) -> str:
        return str(self.get("trace_id", ""))

    @property
    def queue_wait_seconds(self) -> float:
        return float(self.get("queue_wait_seconds", 0.0))

    @property
    def age_seconds(self) -> float:
        return float(self.get("age_seconds", 0.0))

    @property
    def progress_age_seconds(self) -> float:
        return float(self.get("progress_age_seconds", 0.0))

    @property
    def exit_code(self) -> int | None:
        code = self.get("exit_code")
        return None if code is None else int(code)

    @property
    def cache_hit_ratio(self) -> float:
        return float(self.get("cache", {}).get("kv_hit_ratio", 0.0))


class WorkerBuilds(dict):
    """``GET /builds`` payload: queue state + typed build rows."""

    @property
    def queue_depth(self) -> int:
        return int(self.get("queue_depth", 0))

    @property
    def max_concurrent_builds(self) -> int:
        return int(self.get("max_concurrent_builds", 0))

    @property
    def inflight(self) -> list[BuildInfo]:
        return [BuildInfo(b) for b in self.get("inflight", [])]

    @property
    def recent(self) -> list[BuildInfo]:
        return [BuildInfo(b) for b in self.get("recent", [])]


class WorkerClient:
    def __init__(self, socket_path: str,
                 local_shared_path: str = "",
                 worker_shared_path: str = "",
                 timeout: float = 3600.0,
                 connect_timeout: float = 5.0,
                 control_timeout: float = 15.0,
                 retries: int = 2) -> None:
        self.socket_path = socket_path
        self.local_shared_path = local_shared_path
        self.worker_shared_path = worker_shared_path
        # `timeout` is the read timeout for the /build stream (a slow
        # build's frames may be minutes apart); control-plane GETs
        # (/healthz, /builds, /metrics, ...) use the much shorter
        # `control_timeout` — a dashboard poll or a scheduler health
        # probe hanging for an hour against a wedged worker is exactly
        # the failure mode the fleet needs surfaced promptly.
        self.timeout = timeout
        self.connect_timeout = connect_timeout
        self.control_timeout = control_timeout
        # Bounded retry budget for transient socket errors on
        # idempotent control-plane requests (0 disables).
        self.retries = max(int(retries), 0)
        # Terminal payload of the last build() call: exit_code and
        # elapsed_seconds as data, no log-text parsing needed.
        self.last_build: dict = {}
        # Build events (span open/close, steps, cache outcomes) streamed
        # by the last build() call, in arrival order.
        self.last_events: list[dict] = []

    def _request(self, method: str, path: str, body: bytes | None = None,
                 tenant: str = "", headers: dict | None = None,
                 timeout: float | None = None, retry: bool = False):
        hdrs = dict(headers or {})
        if body:
            hdrs.setdefault("Content-Type", "application/json")
        if tenant:
            hdrs["X-Makisu-Tenant"] = tenant
        attempts = 1 + (self.retries if retry else 0)
        for attempt in range(attempts):
            conn = _UnixHTTPConnection(
                self.socket_path,
                self.timeout if timeout is None else timeout,
                connect_timeout=self.connect_timeout)
            try:
                conn.request(method, path, body=body, headers=hdrs)
                return conn, conn.getresponse()
            except _TRANSIENT_ERRORS:
                conn.close()
                if attempt + 1 >= attempts:
                    raise
                time.sleep(0.05 * (attempt + 1))

    def _control(self, path: str):
        """Idempotent control-plane GET: short timeout, bounded
        retry on transient socket errors."""
        return self._request("GET", path,
                             timeout=self.control_timeout, retry=True)

    def ready(self) -> bool:
        try:
            # No retry: ready() is the poll primitive — each call must
            # answer promptly so spin-wait loops keep their cadence.
            conn, resp = self._request("GET", "/ready",
                                       timeout=self.control_timeout)
            try:
                resp.read()
                return resp.status == 200
            finally:
                conn.close()
        except (OSError, http.client.HTTPException):
            return False

    def metrics(self) -> str:
        """The worker's Prometheus text exposition (``GET /metrics``)."""
        conn, resp = self._control("/metrics")
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /metrics returned {resp.status}")
            return resp.read().decode()
        finally:
            conn.close()

    def exit(self) -> None:
        try:
            conn, resp = self._request("GET", "/exit")
            try:
                resp.read()
            finally:
                conn.close()
        except (OSError, http.client.HTTPException):
            pass  # the worker may shut down before the response lands

    def prepare_context(self, context_dir: str) -> str:
        """Copy the build context into the shared mount and return the
        path the worker sees (reference: prepareContext:141)."""
        if not self.local_shared_path:
            return context_dir
        name = os.path.basename(os.path.normpath(context_dir)) or "context"
        local_dst = os.path.join(self.local_shared_path, name)
        fileio.Copier([]).copy_dir(context_dir, local_dst)
        return os.path.join(self.worker_shared_path or
                            self.local_shared_path, name)

    def healthz(self) -> WorkerHealth:
        """The worker's ``GET /healthz`` payload: uptime, build
        outcome counts, and the admission queue's depth/latency
        digests — typed via :class:`WorkerHealth` (still a dict)."""
        conn, resp = self._control("/healthz")
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /healthz returned {resp.status}")
            return WorkerHealth(json.loads(resp.read()))
        finally:
            conn.close()

    def sessions(self) -> dict:
        """The worker's ``GET /sessions`` payload: per-context
        resident build sessions (builds served, hits, resident bytes,
        dirty-tracker mode) plus invalidation tallies."""
        conn, resp = self._control("/sessions")
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /sessions returned {resp.status}")
            return json.loads(resp.read())
        finally:
            conn.close()

    def storage(self, eviction_budget: int | None = None) -> dict:
        """The worker's ``GET /storage`` payload: per-storage-dir
        census + reference audit (+ eviction dry-run when a budget is
        given) and the latest scrub cycle — the full document behind
        /healthz's cached ``storage`` digest."""
        path = "/storage"
        if eviction_budget is not None:
            path += f"?eviction_budget={int(eviction_budget)}"
        conn, resp = self._control(path)
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /storage returned {resp.status}")
            return json.loads(resp.read())
        finally:
            conn.close()

    def invalidate_sessions(self, context: str = "") -> int:
        """Drop the named context's resident session (or every idle
        session when ``context`` is empty); returns the dropped
        count (``POST /sessions/invalidate``)."""
        body = json.dumps({"context": context}).encode()
        conn, resp = self._request("POST", "/sessions/invalidate",
                                   body)
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /sessions/invalidate returned "
                    f"{resp.status}")
            return int(json.loads(resp.read()).get("invalidated", 0))
        finally:
            conn.close()

    def session_snapshot(self, context: str) -> dict:
        """Fetch the named context's session-snapshot recipe
        (``GET /sessions/snapshot?context=``) — the chunk-plan
        document the fleet prewarm path pushes at a target worker.
        404 (no snapshot on disk) raises like every other non-200."""
        from urllib.parse import quote
        conn, resp = self._control(
            f"/sessions/snapshot?context={quote(context, safe='')}")
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /sessions/snapshot returned {resp.status}")
            return json.loads(resp.read())
        finally:
            conn.close()

    def snapshot_sessions(self, context: str = "") -> dict:
        """Checkpoint resident session state into the chunk-addressed
        snapshot plane (``POST /sessions/snapshot``): the named
        context's session, or every idle session when ``context`` is
        empty. Returns ``{"snapshotted": N}``."""
        body = json.dumps({"context": context}).encode()
        conn, resp = self._request("POST", "/sessions/snapshot", body,
                                   timeout=self.control_timeout)
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /sessions/snapshot returned {resp.status}")
            return json.loads(resp.read())
        finally:
            conn.close()

    def restore_session(self, payload: dict) -> dict:
        """Stage a session snapshot onto this worker
        (``POST /sessions/restore``) so the NEXT build on the context
        restores warm. ``payload`` is ``{"recipe": {...}}`` (prewarm
        push: chunks are fetched over the peer wire before the recipe
        lands) or ``{"context": dir}`` (re-validate a recipe already
        on this worker's storage). Returns ``{"ok": bool, "reason"}``;
        refusals are data, not HTTP errors."""
        body = json.dumps(payload).encode()
        conn, resp = self._request("POST", "/sessions/restore", body,
                                   timeout=self.control_timeout)
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /sessions/restore returned {resp.status}")
            return json.loads(resp.read())
        finally:
            conn.close()

    def builds(self) -> WorkerBuilds:
        """The worker's ``GET /builds`` payload: in-flight + recently
        finished builds (tenant, phase, queue wait, progress age,
        cache economics) plus queue depth/cap."""
        conn, resp = self._control("/builds")
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /builds returned {resp.status}")
            return WorkerBuilds(json.loads(resp.read()))
        finally:
            conn.close()

    def profile(self, seconds: float = 5.0) -> dict:
        """An on-demand profile capture (``GET /profile?seconds=N``):
        the worker — or, through the front door, every alive worker
        merged — samples for ``seconds`` and answers with a
        ``makisu-tpu.profile.v1`` window. No retry (a timed-out
        capture must not silently run twice), and the socket timeout
        stretches past the window the server is deliberately
        holding the request for."""
        conn, resp = self._request(
            "GET", f"/profile?seconds={float(seconds):g}",
            timeout=self.control_timeout + float(seconds))
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /profile returned {resp.status}")
            return json.loads(resp.read())
        finally:
            conn.close()

    def alerts(self) -> dict:
        """The ``GET /alerts`` payload: active + recently-resolved
        SLO alerts (worker or fleet server — both speak the same
        ``makisu-tpu.alert.v1`` shape)."""
        conn, resp = self._control("/alerts")
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /alerts returned {resp.status}")
            return json.loads(resp.read())
        finally:
            conn.close()

    def build(self, argv: list[str],
              context_dir: str | None = None,
              on_line=None, on_event=None,
              tenant: str = "", no_wait: bool = False) -> int:
        """Submit a build; stream log lines to the local logger (and
        ``on_line(payload)`` when given); return the worker's build exit
        code.

        The response stream carries three frame types, all NDJSON:
        log lines, build events (``{"event": {...}}`` — collected into
        ``last_events`` and forwarded to ``on_event`` when given), and
        the terminal outcome (``{"build_code": ...}`` — also carrying
        ``queue_wait_seconds`` + ``tenant``, see ``last_build``).

        ``tenant`` labels this build in the worker's queue/latency
        telemetry (sent as the ``X-Makisu-Tenant`` header).
        ``no_wait`` asks for cooperative admission refusal (the fleet
        forwarder's ``X-Makisu-No-Wait``): a saturated worker answers
        503 immediately — surfaced here as the ``RuntimeError`` the
        non-200 path already raises — instead of queueing the build.
        The canary driver probes with it so a wedged worker reads as
        an instant failure, not a piled-up queue."""
        if context_dir is not None:
            worker_ctx = self.prepare_context(context_dir)
            argv = list(argv) + [worker_ctx]
        self.last_build = {}  # stale outcome must not survive a retry
        self.last_events = []
        # The caller's current trace context rides along so the
        # worker-side build ADOPTS it (one trace id across client and
        # worker — loadgen/bench/fleet stitch for free). Only when the
        # caller HAS an explicit context (bound registry or open
        # span): attaching the process-global fallback id would merge
        # every build a bare process submits into one trace.
        from makisu_tpu.utils import metrics
        headers = {}
        if metrics.has_trace_context():
            headers["traceparent"] = metrics.current_traceparent()
        if no_wait:
            headers["X-Makisu-No-Wait"] = "1"
        conn, resp = self._request(
            "POST", "/build", json.dumps(argv).encode(),
            tenant=tenant, headers=headers)
        build_code = 1
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /build returned {resp.status}")
            for line in iter_stream_lines(resp):
                try:
                    payload = json.loads(line)
                except ValueError:
                    log.info(line.decode(errors="replace"))
                    continue
                if "build_code" in payload:
                    build_code = terminal_exit_code(payload)
                    self.last_build = payload
                elif "event" in payload:
                    self.last_events.append(payload["event"])
                    if on_event is not None:
                        on_event(payload["event"])
                else:
                    if on_line is not None:
                        on_line(payload)
                    log.info("[worker] %s", payload.get("msg", line))
        finally:
            conn.close()
        return build_code
