"""Worker client: submit builds to a long-lived worker.

Reference: lib/client/client.go (MakisuClient{Ready,Build,Exit}:36-61,
context copy into the shared mount prepareContext:141, log streaming +
build_code extraction readLines:160-191).
"""

from __future__ import annotations

import http.client
import json
import os
import socket

from makisu_tpu.utils import fileio
from makisu_tpu.utils import logging as log


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, path: str, timeout: float) -> None:
        super().__init__("localhost", timeout=timeout)
        self._path = path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._path)
        self.sock = sock


class WorkerClient:
    def __init__(self, socket_path: str,
                 local_shared_path: str = "",
                 worker_shared_path: str = "",
                 timeout: float = 3600.0) -> None:
        self.socket_path = socket_path
        self.local_shared_path = local_shared_path
        self.worker_shared_path = worker_shared_path
        self.timeout = timeout
        # Terminal payload of the last build() call: exit_code and
        # elapsed_seconds as data, no log-text parsing needed.
        self.last_build: dict = {}
        # Build events (span open/close, steps, cache outcomes) streamed
        # by the last build() call, in arrival order.
        self.last_events: list[dict] = []

    def _request(self, method: str, path: str, body: bytes | None = None):
        conn = _UnixHTTPConnection(self.socket_path, self.timeout)
        conn.request(method, path, body=body,
                     headers={"Content-Type": "application/json"}
                     if body else {})
        return conn, conn.getresponse()

    def ready(self) -> bool:
        try:
            conn, resp = self._request("GET", "/ready")
            try:
                resp.read()
                return resp.status == 200
            finally:
                conn.close()
        except OSError:
            return False

    def metrics(self) -> str:
        """The worker's Prometheus text exposition (``GET /metrics``)."""
        conn, resp = self._request("GET", "/metrics")
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /metrics returned {resp.status}")
            return resp.read().decode()
        finally:
            conn.close()

    def exit(self) -> None:
        try:
            conn, resp = self._request("GET", "/exit")
            try:
                resp.read()
            finally:
                conn.close()
        except (OSError, http.client.HTTPException):
            pass  # the worker may shut down before the response lands

    def prepare_context(self, context_dir: str) -> str:
        """Copy the build context into the shared mount and return the
        path the worker sees (reference: prepareContext:141)."""
        if not self.local_shared_path:
            return context_dir
        name = os.path.basename(os.path.normpath(context_dir)) or "context"
        local_dst = os.path.join(self.local_shared_path, name)
        fileio.Copier([]).copy_dir(context_dir, local_dst)
        return os.path.join(self.worker_shared_path or
                            self.local_shared_path, name)

    def healthz(self) -> dict:
        """The worker's ``GET /healthz`` payload: uptime plus builds
        started/succeeded/failed/active."""
        conn, resp = self._request("GET", "/healthz")
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /healthz returned {resp.status}")
            return json.loads(resp.read())
        finally:
            conn.close()

    def build(self, argv: list[str],
              context_dir: str | None = None,
              on_line=None, on_event=None) -> int:
        """Submit a build; stream log lines to the local logger (and
        ``on_line(payload)`` when given); return the worker's build exit
        code.

        The response stream carries three frame types, all NDJSON:
        log lines, build events (``{"event": {...}}`` — collected into
        ``last_events`` and forwarded to ``on_event`` when given), and
        the terminal outcome (``{"build_code": ...}``)."""
        if context_dir is not None:
            worker_ctx = self.prepare_context(context_dir)
            argv = list(argv) + [worker_ctx]
        self.last_build = {}  # stale outcome must not survive a retry
        self.last_events = []
        conn, resp = self._request("POST", "/build",
                                   json.dumps(argv).encode())
        build_code = 1
        try:
            if resp.status != 200:
                raise RuntimeError(
                    f"worker /build returned {resp.status}")
            buf = b""
            while True:
                chunk = resp.read(4096)
                if not chunk:
                    break
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    try:
                        payload = json.loads(line)
                    except ValueError:
                        log.info(line.decode(errors="replace"))
                        continue
                    if "build_code" in payload:
                        build_code = int(payload["build_code"])
                        self.last_build = payload
                    elif "event" in payload:
                        self.last_events.append(payload["event"])
                        if on_event is not None:
                            on_event(payload["event"])
                    else:
                        if on_line is not None:
                            on_line(payload)
                        log.info("[worker] %s", payload.get("msg", line))
        finally:
            conn.close()
        return build_code
