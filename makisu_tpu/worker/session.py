"""Resident build sessions: keep a context's expensive warm state alive
across builds so the warm path is actually warm.

Every rebuild used to pay full startup, a complete context re-scan, and
re-chunking of untouched regions even when the worker process never
died (ROADMAP item 5). A **build session** — keyed by context path +
the resolved flag identity — keeps resident, per context:

- the stat/content-ID cache (``utils/statcache.ContentIDCache``): no
  JSON reload of 100k entries per build;
- the context-scan memo: per ADD/COPY source subtree, the cache-ID
  checksum transition ``(source, checksum_in) → checksum_out`` — an
  untouched subtree's contribution replays in O(1) with zero syscalls;
- the MemFS layer-replay memo: the header sequence of every applied
  layer keyed by blob digest, so a cached layer folds into the MemFS
  tree without re-inflating the blob or re-parsing the tar;
- the dirty-set tracker: an inotify watcher (ctypes, Linux) with a
  portable mtime-walk delta fallback (``snapshot.walk.snapshot_delta``)
  accumulating changed paths between builds.

The resolved native/JAX runtime stays resident for free (the worker is
one process); the session records its identity so an ISA/ABI flip
invalidates rather than silently mixing routes.

Invalidation story (every reason labels
``makisu_session_invalidations_total``):

- ``flag_identity``: same context, different resolved build flags;
- ``isa_change``: the native ISA/ABI route moved under the process;
- ``ttl``: idle beyond ``MAKISU_TPU_SESSION_TTL`` seconds;
- ``lru``: evicted past ``MAKISU_TPU_SESSION_MAX`` sessions or the
  ``MAKISU_TPU_SESSION_MAX_MB`` resident-byte budget (accounted on
  ``/healthz``);
- ``explicit``: ``POST /sessions/invalidate`` or a manager reset.

Correctness contract: a session only ever REPLAYS state that is a pure
function of inputs that provably didn't change (stat signatures with
the racily-clean discipline, digest-keyed layer headers), so image
digests are byte-identical to a cold build at every point — asserted
by the dirty-set tests and the ``northstar_incremental`` bench.
"""

from __future__ import annotations

import contextvars
import ctypes
import ctypes.util
import hashlib
import json
import os
import struct
import threading
import time

import importlib

from makisu_tpu.utils import ledger, metrics
from makisu_tpu.utils import logging as log

# The snapshot package re-exports the walk FUNCTION under the module's
# own name; resolve the MODULE explicitly.
walk_mod = importlib.import_module("makisu_tpu.snapshot.walk")

# Session metric names live in the utils/metrics.py registry (the
# `check` metric-registry invariant: one spelling per series).
SESSION_HITS = metrics.SESSION_HITS
SESSION_INVALIDATIONS = metrics.SESSION_INVALIDATIONS
SESSION_RESIDENT_BYTES = metrics.SESSION_RESIDENT_BYTES

# Rough per-unit resident-byte estimates for the /healthz accounting.
# Exact sizes would need sys.getsizeof walks per build; the budget is a
# safety cap, not a ledger, so stable estimates beat precise churn.
_BYTES_PER_LAYER_ENTRY = 600   # TarInfo + path strings
_BYTES_PER_CONTENT_ID = 200    # statcache entry (key + stat quadruple)
_BYTES_PER_MEMO = 160          # scan-memo key/value

# Scan-memo entries kept per session: keys are (source, checksum_in);
# upstream cache-ID churn mints new keys, so stale ones age out by cap.
_SCAN_MEMO_KEEP = 512


def enabled() -> bool:
    """Resident sessions are on by default (a session that is never
    reused costs one dict entry); MAKISU_TPU_SESSION=0 disables."""
    return os.environ.get("MAKISU_TPU_SESSION", "1") == "1"


def session_ttl() -> float:
    try:
        return float(os.environ.get("MAKISU_TPU_SESSION_TTL", "3600"))
    except ValueError:
        return 3600.0


def max_sessions() -> int:
    try:
        return int(os.environ.get("MAKISU_TPU_SESSION_MAX", "8"))
    except ValueError:
        return 8


def max_resident_bytes() -> int:
    try:
        mb = float(os.environ.get("MAKISU_TPU_SESSION_MAX_MB", "512"))
    except ValueError:
        mb = 512.0
    return int(mb * 1e6)


def max_watches() -> int:
    try:
        return int(os.environ.get("MAKISU_TPU_SESSION_MAX_WATCHES",
                                  "8192"))
    except ValueError:
        return 8192


# This build's residency state for the history record's ``warm_mode``
# label: "resident" (session reused with an exact dirty set), "fresh"
# (new session: first build of this context/identity), "rescan"
# (session reused but dirty knowledge was lost — full re-scan), "off"
# (sessions disabled or bypassed), "none" (non-build command).
_warm_mode: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "makisu_session_warm_mode", default="none")


def warm_mode() -> str:
    return _warm_mode.get()


def set_warm_mode(label: str) -> None:
    _warm_mode.set(label)


def _isa_identity() -> str:
    """The native route identity a session was built under. Only what
    is ALREADY resolved: sessions must not force a native-library load
    (cheap commands never pay `make`)."""
    from makisu_tpu import native
    return native.isa_route_if_resolved() or "unresolved"


def _identity_dict(args, gzip_backend_id: str) -> dict:
    return {
        "context": os.path.abspath(args.context),
        "root": os.path.abspath(args.root),
        "dockerfile": os.path.abspath(
            args.file or os.path.join(args.context, "Dockerfile")),
        "hasher": args.hasher,
        "gzip_backend_id": gzip_backend_id,
        "modifyfs": bool(args.modifyfs),
        "commit": args.commit,
        "target": args.target,
        "build_args": sorted(args.build_arg),
        "blacklist": sorted(args.blacklist),
    }


def _digest_identity(ident: dict) -> str:
    blob = json.dumps(ident, sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def identity_from_build_args(args, storage_dir: str,
                             gzip_backend_id: str) -> str:
    """Stable digest of the resolved flags that shape build identity
    for one context. Anything here that moves mints a new session
    (reason=flag_identity) — mixing, say, two hashers' warm state
    would be silently wrong."""
    ident = _identity_dict(args, gzip_backend_id)
    ident["storage"] = os.path.abspath(storage_dir)
    return _digest_identity(ident)


def portable_identity_from_build_args(args,
                                      gzip_backend_id: str) -> str:
    """The flag identity MINUS the storage dir: the fleet front door
    rewrites ``--storage`` per worker, so the full identity of one
    logical build differs across workers. Session snapshots key and
    validate on this portable form — everything that shapes build
    OUTPUT is still in it, only the machine-local storage location is
    not (a restored memo never depends on where chunks happen to
    live)."""
    return _digest_identity(_identity_dict(args, gzip_backend_id))


def snapshot_policy() -> str:
    """MAKISU_TPU_SESSION_SNAPSHOT: "1" checkpoints every successful
    build, "0" disables the snapshot plane entirely, default "auto"
    checkpoints only residency-hinted sessions (worker / --watch /
    repeat builds) — a one-shot CLI build on a cold host skips the
    serialization it could never redeem."""
    return os.environ.get("MAKISU_TPU_SESSION_SNAPSHOT", "auto")


# -- inotify watcher --------------------------------------------------------

_IN_ACCESS = 0x00000001
_IN_MODIFY = 0x00000002
_IN_ATTRIB = 0x00000004
_IN_CLOSE_WRITE = 0x00000008
_IN_MOVED_FROM = 0x00000040
_IN_MOVED_TO = 0x00000080
_IN_CREATE = 0x00000100
_IN_DELETE = 0x00000200
_IN_DELETE_SELF = 0x00000400
_IN_MOVE_SELF = 0x00000800
_IN_ISDIR = 0x40000000
_IN_Q_OVERFLOW = 0x00004000
_IN_IGNORED = 0x00008000
_IN_NONBLOCK = 0x00000800  # O_NONBLOCK on linux
_IN_CLOEXEC = 0x00080000   # O_CLOEXEC on linux

_WATCH_MASK = (_IN_MODIFY | _IN_ATTRIB | _IN_CLOSE_WRITE
               | _IN_MOVED_FROM | _IN_MOVED_TO | _IN_CREATE
               | _IN_DELETE | _IN_DELETE_SELF | _IN_MOVE_SELF)

_EVENT_HDR = struct.Struct("iIII")  # wd, mask, cookie, len


def _libc():
    name = ctypes.util.find_library("c")
    return ctypes.CDLL(name, use_errno=True) if name else None


class InotifyWatcher:
    """Recursive inotify watch over a context tree. Best-effort by
    design: any failure (no inotify, watch-limit ENOSPC, queue
    overflow, structural events that stale the wd→path map) flips
    ``healthy`` off and the session falls back to the mtime-walk
    delta. ``collect()`` drains pending events into a dirty-path set;
    ``resync()`` (after a build) re-registers watches so directories
    created between builds are covered going forward."""

    def __init__(self, root: str, blacklist: list[str]) -> None:
        self.root = root
        self.blacklist = list(blacklist)
        self.healthy = False
        self._fd = -1
        self._wd_paths: dict[int, str] = {}
        self._needs_resync = False
        self._libc = _libc()
        if self._libc is None or not hasattr(self._libc,
                                             "inotify_init1"):
            return
        fd = self._libc.inotify_init1(_IN_NONBLOCK | _IN_CLOEXEC)
        if fd < 0:
            return
        self._fd = fd
        self.healthy = self._add_watches()
        if not self.healthy:
            self.close()

    def _dirs(self) -> list[str]:
        """Directory list via a stat-free scandir descent (dirent type
        bits only): registering watches over a 100k-file tree must not
        pay a full per-file lstat walk."""
        from makisu_tpu.utils import pathutils
        dirs = [self.root]
        stack = [self.root]
        limit = max_watches()
        try:
            while stack:
                cur = stack.pop()
                with os.scandir(cur) as it:
                    for entry in it:
                        if not entry.is_dir(follow_symlinks=False):
                            continue
                        if pathutils.is_descendant_of_any(
                                entry.path, self.blacklist):
                            continue
                        dirs.append(entry.path)
                        if len(dirs) > limit:
                            return dirs  # caller sees > cap and bails
                        stack.append(entry.path)
        except OSError:
            return []
        return dirs

    def _add_watches(self) -> bool:
        dirs = self._dirs()
        if not dirs or len(dirs) > max_watches():
            return False
        for path in dirs:
            wd = self._libc.inotify_add_watch(
                self._fd, path.encode(), _WATCH_MASK)
            if wd < 0:
                return False  # ENOSPC / vanished dir: fall back whole
            self._wd_paths[wd] = path
        return True

    def collect(self) -> set[str] | None:
        """Drain events into dirty paths. ``None`` means knowledge was
        lost (overflow, read error, structural staleness) — callers
        must fall back to a full re-scan."""
        if not self.healthy:
            return None
        dirty: set[str] = set()
        structural = False
        while True:
            try:
                buf = os.read(self._fd, 65536)
            except BlockingIOError:
                break
            except OSError:
                self.healthy = False
                return None
            if not buf:
                break
            off = 0
            while off + _EVENT_HDR.size <= len(buf):
                wd, mask, _cookie, nlen = _EVENT_HDR.unpack_from(
                    buf, off)
                name = buf[off + _EVENT_HDR.size:
                           off + _EVENT_HDR.size + nlen].rstrip(b"\0")
                off += _EVENT_HDR.size + nlen
                if mask & _IN_Q_OVERFLOW:
                    self.healthy = False
                    return None
                base = self._wd_paths.get(wd)
                if mask & _IN_IGNORED:
                    self._wd_paths.pop(wd, None)
                    structural = True
                    continue
                if base is None:
                    continue
                path = (os.path.join(base, name.decode(
                    errors="surrogateescape")) if name else base)
                dirty.add(path)
                if mask & (_IN_ISDIR | _IN_DELETE_SELF
                           | _IN_MOVE_SELF):
                    # A directory appeared/vanished/moved: its
                    # subtree's future events are unreliable until
                    # watches re-register (resync after the build).
                    # The dir itself is dirty, which forces the
                    # containing source to re-walk — correctness holds
                    # without per-event watch surgery.
                    structural = True
        if structural:
            self._needs_resync = True
        return dirty

    def resync(self) -> None:
        """Re-register watches after structural churn (directory
        create/delete/rename staled the wd→path map or left subtrees
        unwatched). NO-OP on the steady path: without a structural
        event no new directories can exist, so a stable tree pays
        nothing per build — the per-build full-tree walk this replaces
        was itself a warm-floor term at 100k files."""
        if not self.healthy or not self._needs_resync:
            return
        for wd in list(self._wd_paths):
            self._libc.inotify_rm_watch(self._fd, wd)
        self._wd_paths.clear()
        self._needs_resync = False
        self.healthy = self._add_watches()

    def close(self) -> None:
        if self._fd >= 0:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = -1
        self.healthy = False


# -- the session ------------------------------------------------------------


class BuildSession:
    """One context's resident warm state. Single-writer: the manager
    hands a session to at most one build at a time (concurrent builds
    of the same context bypass with reason=busy)."""

    def __init__(self, context_dir: str, identity: str) -> None:
        self.context_dir = context_dir
        self.identity = identity
        self.isa = _isa_identity()
        self.created_mono = time.monotonic()
        self.last_used_mono = self.created_mono
        self.builds = 0
        self.hits = 0
        self.busy = False
        # Resident state.
        self.content_ids = None  # adopted from the first BuildContext
        self.scan_memo: dict[tuple[str, int],
                             tuple[int, int, int]] = {}
        # Applied-layer op streams keyed by (applied-chain, digest):
        # valid only at the exact chain position they were recorded at
        # (builder/node.py holds the correctness argument).
        self.layer_replay: dict[tuple[str, str], list] = {}
        self._layer_entry_count = 0
        self.snapshot: walk_mod.TreeSnapshot | None = None
        self.watcher: InotifyWatcher | None = None
        self.pending_dirty: set[str] = set()
        # True iff the dirty set provably covers every change since the
        # last successful build; False forces a full re-scan.
        self.exact = False
        self._ignore_sig = None  # .dockerignore content hash
        self._walk_blacklist: list[str] = []
        # Whether arming expensive tracking (the full-walk baseline)
        # is worth it: set per build from resident_process / repeat use.
        self._resident_hint = False
        # -- session-snapshot plane (worker/snapshots.py) --
        # The portable flag identity + storage dir arrive with the
        # lease; without them the snapshot plane stays dark.
        self.portable_identity: str | None = None
        self.storage_dir: str | None = None
        # True for the first build after a snapshot restore: reported
        # as warm_mode=restored. The companion flag below survives
        # until the first release(), where a byte-budget eviction the
        # restore caused labels lru_restore instead of plain lru.
        self.restored = False
        self._restore_fresh = False
        # Restored stat-cache entries, merged into the context's
        # content-ID cache at the next begin_build (the cache instance
        # doesn't exist until a build arrives).
        self._restored_stat_entries: dict | None = None
        # A restored walk baseline certifies a PAST point; the next
        # poll must delta against it once before trusting the watcher.
        self._gap_delta_pending = False
        # Incremental-write bookkeeping: previous checkpoint's shard
        # chunks (carry-forward), dirty flags per shard family, and the
        # watcher-mode persistence baseline (the live watcher session
        # needs no walk; snapshots do).
        self._snap_shards: dict[str, dict] = {}
        self._snap_scan_dirty = True
        self._snap_stat_all = True
        self._snap_walk_dirty: set[str] = set()
        self._snap_walk_all = True
        self._snap_baseline: walk_mod.TreeSnapshot | None = None
        self._snap_gap_paths = 0

    # -- accounting --

    def resident_bytes(self) -> int:
        n = self._layer_entry_count * _BYTES_PER_LAYER_ENTRY
        n += len(self.scan_memo) * _BYTES_PER_MEMO
        if self.content_ids is not None:
            n += (len(getattr(self.content_ids, "_entries", None) or ())
                  * _BYTES_PER_CONTENT_ID)
        if self.snapshot is not None:
            n += self.snapshot.approx_bytes()
        return n

    def stats(self) -> dict:
        now = time.monotonic()
        return {
            "context": self.context_dir,
            "identity": self.identity,
            "isa": self.isa,
            "builds": self.builds,
            "hits": self.hits,
            "resident_bytes": self.resident_bytes(),
            "layers_cached": len(self.layer_replay),
            "scan_memo_entries": len(self.scan_memo),
            "dirty_pending": len(self.pending_dirty),
            "dirty_exact": self.exact,
            "watcher": ("inotify" if self.watcher is not None
                        and self.watcher.healthy else "mtime-walk"),
            "age_seconds": round(now - self.created_mono, 3),
            "idle_seconds": round(now - self.last_used_mono, 3),
            "busy": self.busy,
        }

    # -- dirty tracking --

    def _ignore_signature(self):
        path = os.path.join(self.context_dir, ".dockerignore")
        try:
            with open(path, "rb") as f:
                return hashlib.sha256(f.read()).hexdigest()
        except OSError:
            return None

    def poll_changes(self) -> set[str]:
        """Accumulate changes since the last poll/build into
        ``pending_dirty`` and return the signature-confirmed NEW dirt
        from this poll (what a watch loop triggers on). Watcher events
        when healthy; one mtime-walk delta otherwise.

        Knowledge loss — watcher overflow/death, a failed delta walk,
        or no baseline at all — NEVER goes silent: the session turns
        inexact, the whole context is flagged dirty once (so the next
        build re-scans everything and a watch loop rebuilds), and a
        fresh walk baseline is seeded so tracking resumes."""
        gap_dirty: set[str] = set()
        if self._gap_delta_pending and self.snapshot is not None:
            # Restored session: the persisted baseline certifies the
            # state at snapshot time — one delta against it surfaces
            # everything that moved in the snapshot→restore gap at the
            # same trust level the live mtime-walk fallback has. Only
            # after it runs may a (freshly created, gap-blind) watcher
            # be believed.
            self._gap_delta_pending = False
            try:
                self.snapshot, delta = walk_mod.snapshot_delta(
                    self.snapshot, self._walk_blacklist)
            except OSError:
                self.snapshot = None
                self.exact = False
                self.pending_dirty.add(self.context_dir)
                self._snap_walk_all = True
                return {self.context_dir}
            gap_dirty = delta.dirty
            self.pending_dirty |= gap_dirty
            self._snap_walk_dirty |= gap_dirty
        if self.watcher is not None and self.watcher.healthy:
            got = self.watcher.collect()
            if got is not None:
                self.pending_dirty |= got
                self._snap_gap_paths += len(got)
                # New dirs appeared? Register their watches BEFORE the
                # caller scans, so edits inside them during the build
                # are evented (no-op without structural churn).
                self.watcher.resync()
                if self.watcher.healthy:
                    return got | gap_dirty
            # Overflow / read error / resync failure: the watcher is
            # dead — release its fd + kernel watches (a long-lived
            # worker must not pin inotify limits on corpses) and fall
            # through to re-seed the walk baseline.
            self.watcher.close()
        if self.snapshot is not None:
            try:
                self.snapshot, delta = walk_mod.snapshot_delta(
                    self.snapshot, self._walk_blacklist)
            except OSError:
                self.snapshot = None
                self.exact = False
                self.pending_dirty.add(self.context_dir)
                self._snap_walk_all = True
                return {self.context_dir}
            self.pending_dirty |= delta.dirty
            self._snap_walk_dirty |= delta.dirty
            return delta.real_dirty | gap_dirty
        # No baseline: what changed since the last certified point is
        # unknowable — flag everything once and re-baseline. The
        # baseline walk (a full lstat pass) only runs when residency
        # can pay it back: a resident process, or an in-process repeat
        # build. A one-shot CLI build on a watcher-less host skips it
        # — it would be a 100k-file walk armed for a process about to
        # exit.
        self.exact = False
        self.pending_dirty.add(self.context_dir)
        if self._resident_hint:
            try:
                self.snapshot = walk_mod.snapshot_tree(
                    self.context_dir, self._walk_blacklist)
            except OSError:
                self.snapshot = None
        return {self.context_dir}

    # -- build lifecycle --

    def begin_build(self, ctx, resident_process: bool = False) -> str:
        """Arm ``ctx`` with this session's resident state. Returns the
        warm mode this build runs under ("resident" | "rescan").
        ``resident_process`` (worker / --watch) additionally defers
        statcache persistence to a background thread — a one-shot CLI
        process must keep the synchronous save or it may exit before
        the write lands."""
        self.builds += 1
        self.last_used_mono = time.monotonic()
        self._resident_hint = resident_process or self.builds >= 2
        self.storage_dir = ctx.image_store.root
        self._walk_blacklist = [
            p for p in (list(ctx.base_blacklist)
                        + [ctx.image_store.root])
            if p != ctx.context_dir]
        # The tracker must exist BEFORE this build's scan reads any
        # file: an edit landing mid-build (after the scan passed it)
        # must surface in the NEXT build's dirty set — watcher events
        # queue in the kernel; the walk baseline below is captured
        # pre-scan so the next delta re-examines anything that moved
        # after it. A baseline taken after the build would absorb
        # mid-build edits and replay a stale scan memo.
        if self.watcher is None:
            self.watcher = InotifyWatcher(self.context_dir,
                                          self._walk_blacklist)
            if not self.watcher.healthy:
                self.watcher.close()
        self.poll_changes()
        # .dockerignore governs which paths enter cache identity but
        # lives OUTSIDE the per-source subtrees, so the scan memo can't
        # see it change through the dirty containment check — hash it
        # every build and drop the memo on any change.
        ignore_sig = self._ignore_signature()
        if ignore_sig != self._ignore_sig:
            if self._ignore_sig is not None or ignore_sig is not None:
                self.scan_memo.clear()
                self._snap_scan_dirty = True
            self._ignore_sig = ignore_sig
        # Adopt or install the resident content-ID cache.
        if self.content_ids is None:
            self.content_ids = ctx.content_ids
        else:
            ctx.content_ids = self.content_ids
        # Snapshot-restored stat entries merge on first use —
        # setdefault semantics (local knowledge wins), and every
        # adopted entry still faces the per-lookup stat comparison and
        # racily-clean window, so a stale restored entry re-hashes
        # instead of replaying.
        if self._restored_stat_entries is not None:
            merge = getattr(self.content_ids, "merge_entries", None)
            if merge is not None:
                merge(self._restored_stat_entries)
            self._restored_stat_entries = None
        begin = getattr(self.content_ids, "begin_build", None)
        if begin is not None:
            begin()
        # Resident process: the statcache's disk copy is durability
        # only — persist it off the build's critical path.
        if resident_process:
            self.content_ids.defer_save = True
        mode = "resident" if self.exact else "rescan"
        if self.restored:
            # First build after a snapshot restore: same residency
            # semantics as the mode it shadows (dirty_exact still
            # gates the scan memo), but reported distinctly so the
            # fleet can tell a hand-off from a resident hit.
            mode = "restored"
            self.restored = False
        ctx.session = self
        ctx.dirty_paths = frozenset(self.pending_dirty)
        ctx.dirty_exact = self.exact
        if self.exact:
            self.hits += 1
            metrics.counter_add(SESSION_HITS)
        log.info("build session %s: mode=%s dirty=%d builds=%d",
                 self.identity, mode, len(self.pending_dirty),
                 self.builds)
        return mode

    def finish_build(self, ctx, ok: bool) -> None:
        self.last_used_mono = time.monotonic()
        if ok:
            # Everything dirty was consumed by this build's scan.
            self.pending_dirty.clear()
            if self.watcher is not None and self.watcher.healthy:
                # Mid-build edits are drained AND kept pending: the
                # scan may have read a file before the racing write
                # landed — one conservative extra re-hash, never a
                # stale identity. Collect runs BEFORE resync so a
                # raced structural event (new dir) triggers the watch
                # rebuild.
                raced = self.watcher.collect()
                self.watcher.resync()
                if raced is None or not self.watcher.healthy:
                    # Watcher died at the finish line: the next
                    # begin's poll flags the context and re-seeds a
                    # walk baseline.
                    self.watcher.close()
                    self.snapshot = None
                    self.exact = False
                else:
                    self.pending_dirty |= raced
                    self._snap_gap_paths += len(raced)
                    self.exact = True
            else:
                # mtime-walk fallback: the baseline captured at
                # begin_build — BEFORE this build's scan — is the
                # certification point; the next delta re-examines
                # anything that moved after it, including mid-build
                # edits.
                self.exact = self.snapshot is not None
        else:
            # A failed build may have consumed part of the dirty set
            # before dying; only a full re-scan re-certifies it.
            self.exact = False
            self.snapshot = None
            self.pending_dirty.clear()
            self.scan_memo.clear()
            self._snap_scan_dirty = True
            self._snap_walk_all = True
        # The per-build context must not leak a dead session reference.
        ctx.session = None
        ctx.dirty_paths = frozenset()
        ctx.dirty_exact = False
        if ok:
            self.checkpoint()

    def checkpoint(self, force: bool = False) -> dict | None:
        """Write this session's snapshot through the chunk CAS
        (worker/snapshots.py). Incremental — only dirty shards
        re-chunk — and advisory: any failure costs durability, never
        the build. ``force`` (the worker's POST /sessions/snapshot and
        the drain hand-off) checkpoints even sessions the auto policy
        would skip."""
        policy = snapshot_policy()
        if policy == "0" or not self.portable_identity \
                or not self.storage_dir:
            return None
        if not force and policy == "auto" and not self._resident_hint:
            return None
        from makisu_tpu.worker import snapshots as snapshots_mod
        recipe = snapshots_mod.write_snapshot(self, self.storage_dir)
        mgr = manager()
        if recipe is None:
            mgr.note_snapshot("write_error",
                              context=self.context_dir)
        else:
            mgr.note_snapshot("write", context=self.context_dir)
        return recipe

    # -- memo surfaces (called via ctx by steps/memfs/node) --

    def scan_lookup(self, source: str, checksum_in: int):
        key = (source, checksum_in)
        hit = self.scan_memo.get(key)
        if hit is not None:
            # Recency bump (dict insertion order IS the LRU order): a
            # hot key replayed every build must not be evicted by a
            # burst of one-shot keys that arrived after it.
            self.scan_memo.pop(key)
            self.scan_memo[key] = hit
        return hit

    def scan_store(self, source: str, checksum_in: int,
                   checksum_out: int, files: int, nbytes: int) -> None:
        if len(self.scan_memo) >= _SCAN_MEMO_KEEP:
            # Recency-order eviction: the front of the dict is the
            # least recently stored OR replayed key (scan_lookup
            # re-inserts on hit), so stale keys from superseded chains
            # age out first and hot keys survive one-shot bursts.
            self.scan_memo.pop(next(iter(self.scan_memo)))
        self.scan_memo[(source, checksum_in)] = (
            checksum_out, files, nbytes)
        self._snap_scan_dirty = True

    def replay_lookup(self, key: tuple[str, str]):
        return self.layer_replay.get(key)

    def replay_store(self, key: tuple[str, str],
                     entries: list) -> None:
        if key in self.layer_replay:
            return
        self.layer_replay[key] = entries
        self._layer_entry_count += len(entries)

    def evict_layers(self, keep_bytes: int) -> None:
        """Drop oldest layer memos until resident bytes fit."""
        while (self.layer_replay
               and self.resident_bytes() > keep_bytes):
            key, entries = next(iter(self.layer_replay.items()))
            del self.layer_replay[key]
            self._layer_entry_count -= len(entries)

    def close(self) -> None:
        if self.watcher is not None:
            self.watcher.close()
            self.watcher = None


# -- the manager ------------------------------------------------------------


class SessionManager:
    """Process-wide session registry with TTL/LRU/byte-budget
    eviction. One session per context path; acquire is non-blocking —
    a second concurrent build of the same context bypasses residency
    instead of serializing on it."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._sessions: dict[str, BuildSession] = {}
        self.invalidations: dict[str, int] = {}
        # Snapshot-plane accounting (durable for the life of the
        # worker, unlike the event-bus ledger): what /healthz exports
        # and `doctor --fleet`'s snapshot_restore_failed finding reads.
        self.snapshot_counts: dict[str, int] = {}
        self.last_restore_failure: dict = {}

    def note_snapshot(self, event: str, context: str = "",
                      reason: str = "") -> None:
        """Count one snapshot-plane event (write / write_error /
        restore / restore_refused / restore_error); failures retain
        context + reason for the fleet doctor."""
        with self._mu:
            self.snapshot_counts[event] = \
                self.snapshot_counts.get(event, 0) + 1
            if event in ("restore_refused", "restore_error"):
                self.last_restore_failure = {
                    "context": context, "reason": reason,
                    "ts": time.time()}

    def _invalidate_locked(self, key: str, reason: str) -> None:
        session = self._sessions.pop(key, None)
        if session is None:
            return
        session.close()
        self.invalidations[reason] = \
            self.invalidations.get(reason, 0) + 1
        metrics.counter_add(SESSION_INVALIDATIONS, reason=reason)
        ledger.record("session", session.context_dir, "invalidated",
                      reason=reason, builds=session.builds,
                      resident_bytes=session.resident_bytes())
        log.info("build session invalidated: %s (%s)",
                 session.context_dir, reason)

    def _publish_bytes_locked(self) -> None:
        total = sum(s.resident_bytes()
                    for s in self._sessions.values())
        metrics.global_registry().gauge_set(SESSION_RESIDENT_BYTES,
                                            total)

    def acquire(self, context_dir: str, identity: str,
                restore_spec: "tuple[str, str] | None" = None,
                ) -> tuple["BuildSession | None", str]:
        """Lease the context's session for one build. Returns
        ``(session, verdict)`` where verdict is one of ``hit`` (a live
        session was reused), ``restored`` (no resident session, but a
        chunk-addressed snapshot passed every invalidation check and
        was rebuilt), ``miss`` (a new session was created), or
        ``busy`` (another build holds it — caller proceeds without
        residency). ``restore_spec`` is ``(storage_dir,
        portable_identity)``; without it the snapshot plane is never
        consulted."""
        context_dir = os.path.abspath(context_dir)
        key = os.path.realpath(context_dir)
        now = time.monotonic()
        with self._mu:
            session = self._sessions.get(key)
            if session is not None:
                if session.busy:
                    return None, "busy"
                if session.identity != identity:
                    self._invalidate_locked(key, "flag_identity")
                    session = None
                elif session.isa != _isa_identity():
                    self._invalidate_locked(key, "isa_change")
                    session = None
                elif now - session.last_used_mono > session_ttl():
                    self._invalidate_locked(key, "ttl")
                    session = None
            if session is not None:
                if restore_spec is not None:
                    session.portable_identity = restore_spec[1]
                session.busy = True
                self._publish_bytes_locked()
                return session, "hit"
        # Cold miss: consult the snapshot plane OUTSIDE the lock (the
        # shard fetch may ride the fleet peer wire — a slow peer must
        # not stall every other context's acquire).
        restored = None
        if restore_spec is not None and snapshot_policy() != "0":
            restored = self._try_restore(context_dir, identity,
                                         restore_spec)
        with self._mu:
            resident = self._sessions.get(key)
            if resident is not None:
                # A concurrent acquire of the same context won the
                # race while we restored; the resident session is the
                # single writer — ours is discarded.
                if restored is not None:
                    restored.close()
                if resident.busy:
                    return None, "busy"
                session, verdict = resident, "hit"
            else:
                session = restored if restored is not None \
                    else BuildSession(context_dir, identity)
                verdict = "restored" if restored is not None \
                    else "miss"
                if restore_spec is not None:
                    session.portable_identity = restore_spec[1]
                self._sessions[key] = session
                # Count-based LRU: evict the stalest idle session. A
                # restore that pushed the count over budget labels its
                # victims distinctly (lru_restore) so doctor can tell
                # hand-off pressure from plain churn.
                reason = ("lru_restore" if verdict == "restored"
                          else "lru")
                while len(self._sessions) > max(1, max_sessions()):
                    victims = sorted(
                        ((s.last_used_mono, k)
                         for k, s in self._sessions.items()
                         if k != key and not s.busy))
                    if not victims:
                        break
                    self._invalidate_locked(victims[0][1], reason)
            session.busy = True
            self._publish_bytes_locked()
        return session, verdict

    def _try_restore(self, context_dir: str, identity: str,
                     restore_spec: tuple) -> "BuildSession | None":
        """Attempt a snapshot restore outside the manager lock (the
        chunk fetch may ride the peer wire). Counts every outcome;
        ``absent`` (no recipe) is a plain cold miss, not a failure."""
        storage_dir, portable = restore_spec
        from makisu_tpu.worker import snapshots as snapshots_mod
        try:
            session, reason = snapshots_mod.try_restore(
                context_dir, identity, storage_dir, portable)
        except Exception as exc:  # noqa: BLE001 - advisory plane
            log.warning("session snapshot restore errored for %s: %s",
                        context_dir, exc)
            session, reason = None, "error"
        if session is not None:
            self.note_snapshot("restore", context=context_dir)
            metrics.counter_add(metrics.SESSION_SNAPSHOT_RESTORES,
                                result="ok")
            ledger.record("session", context_dir, "restored",
                          reason="snapshot",
                          resident_bytes=session.resident_bytes())
            log.info("build session restored from snapshot: %s "
                     "(exact=%s layers=%d)", context_dir,
                     session.exact, len(session.layer_replay))
            return session
        if reason:
            event = ("restore_error" if reason == "error"
                     else "restore_refused")
            self.note_snapshot(event, context=context_dir,
                               reason=reason)
            metrics.counter_add(
                metrics.SESSION_SNAPSHOT_RESTORES,
                result="refused" if event == "restore_refused"
                else "error", reason=reason)
            ledger.record("session", context_dir, "restore_refused",
                          reason=reason)
            log.info("session snapshot restore refused for %s (%s)",
                     context_dir, reason)
        return None

    def release(self, session: BuildSession) -> None:
        key = os.path.realpath(session.context_dir)
        budget = max_resident_bytes()
        with self._mu:
            session.busy = False
            # Byte-budget evictions caused by a freshly-restored
            # session's resident bytes label lru_restore: the hand-off
            # over-budgeted the worker, which is a sizing signal, not
            # ordinary churn.
            reason = "lru_restore" if session._restore_fresh else "lru"
            session._restore_fresh = False
            # Byte budget: first shrink the releasing session's layer
            # memo, then evict whole idle sessions oldest-first.
            total = sum(s.resident_bytes()
                        for s in self._sessions.values())
            if total > budget:
                session.evict_layers(
                    max(0, budget - (total - session.resident_bytes())))
            while (sum(s.resident_bytes()
                       for s in self._sessions.values()) > budget
                   and len(self._sessions) > 1):
                victims = sorted(
                    ((s.last_used_mono, k)
                     for k, s in self._sessions.items()
                     if k != key and not s.busy))
                if not victims:
                    break
                self._invalidate_locked(victims[0][1], reason)
            self._publish_bytes_locked()

    def peek(self, context_dir: str) -> "BuildSession | None":
        """The context's live session, if any — no lease, no
        invalidation checks (the watch loop polls change state through
        it between builds)."""
        key = os.path.realpath(os.path.abspath(context_dir))
        with self._mu:
            return self._sessions.get(key)

    def storage_dir_for(self, context_dir: str) -> str:
        """The storage dir the named context's resident session is
        bound to ("" when no resident session, or none has built yet)
        — the snapshot endpoints use it to pick the recipe's home
        among a multi-storage worker's dirs."""
        key = os.path.realpath(os.path.abspath(context_dir))
        with self._mu:
            session = self._sessions.get(key)
            return session.storage_dir or "" if session else ""

    def invalidate(self, context_dir: str = "") -> int:
        """Explicit invalidation (the worker's POST endpoint). Empty
        context drops every non-busy session; returns the count."""
        dropped = 0
        with self._mu:
            if context_dir:
                keys = [os.path.realpath(os.path.abspath(context_dir))]
            else:
                keys = list(self._sessions)
            for key in keys:
                session = self._sessions.get(key)
                if session is None or session.busy:
                    continue
                self._invalidate_locked(key, "explicit")
                dropped += 1
            self._publish_bytes_locked()
        return dropped

    def snapshot_all(self, context_dir: str = "",
                     force: bool = True) -> int:
        """Checkpoint every idle resident session (or one context) to
        the snapshot plane NOW — the worker's POST /sessions/snapshot
        and the fleet's drain hand-off. Writes run outside the lock;
        returns the number of sessions checkpointed."""
        want = (os.path.realpath(os.path.abspath(context_dir))
                if context_dir else "")
        with self._mu:
            candidates = [s for k, s in self._sessions.items()
                          if not s.busy and (not want or k == want)]
        done = 0
        for session in candidates:
            if session.checkpoint(force=force) is not None:
                done += 1
        return done

    def stats(self) -> dict:
        """The ``/healthz`` sessions section + ``GET /sessions``."""
        with self._mu:
            sessions = [s.stats() for s in self._sessions.values()]
            # Copied under the lock: a concurrent first-of-its-kind
            # invalidation reason would otherwise mutate the dict mid-
            # iteration and 500 a health probe.
            invalidations = dict(self.invalidations)
            snapshot_counts = dict(self.snapshot_counts)
            last_failure = dict(self.last_restore_failure)
        sessions.sort(key=lambda s: s["context"])
        return {
            "count": len(sessions),
            "resident_bytes": sum(s["resident_bytes"]
                                  for s in sessions),
            "hits": sum(s["hits"] for s in sessions),
            "invalidations": dict(sorted(invalidations.items())),
            "max_sessions": max_sessions(),
            "max_resident_bytes": max_resident_bytes(),
            "ttl_seconds": session_ttl(),
            "snapshot": {
                **{k: snapshot_counts.get(k, 0)
                   for k in ("write", "write_error", "restore",
                             "restore_refused", "restore_error")},
                "last_restore_failure": last_failure,
            },
            "sessions": sessions,
        }

    def reset(self) -> None:
        """Drop everything (tests)."""
        with self._mu:
            for session in self._sessions.values():
                session.close()
            self._sessions.clear()
            self.invalidations.clear()
            self.snapshot_counts.clear()
            self.last_restore_failure = {}
            self._publish_bytes_locked()


_manager = SessionManager()

# Context-bound manager override: a WorkerServer binds ITS OWN
# SessionManager around every build it runs, so multiple in-process
# workers (the fleet loadgen topology, and any test standing up a
# 3-worker fleet in one interpreter) model real machines — each
# worker's resident sessions, /sessions rows, and affinity signal are
# its own, exactly as they would be across separate hosts. Standalone
# CLI builds and --watch keep the process-global manager.
_bound_manager: "contextvars.ContextVar[SessionManager | None]" = \
    contextvars.ContextVar("makisu_session_manager", default=None)


def bind_manager(mgr: SessionManager):
    """Bind ``mgr`` as the current context's session manager (threads
    the build spawns inherit it via ``contextvars.copy_context``).
    Returns a reset token."""
    return _bound_manager.set(mgr)


def reset_manager(token) -> None:
    _bound_manager.reset(token)


def manager() -> SessionManager:
    return _bound_manager.get() or _manager
