"""Resident build sessions: keep a context's expensive warm state alive
across builds so the warm path is actually warm.

Every rebuild used to pay full startup, a complete context re-scan, and
re-chunking of untouched regions even when the worker process never
died (ROADMAP item 5). A **build session** — keyed by context path +
the resolved flag identity — keeps resident, per context:

- the stat/content-ID cache (``utils/statcache.ContentIDCache``): no
  JSON reload of 100k entries per build;
- the context-scan memo: per ADD/COPY source subtree, the cache-ID
  checksum transition ``(source, checksum_in) → checksum_out`` — an
  untouched subtree's contribution replays in O(1) with zero syscalls;
- the MemFS layer-replay memo: the header sequence of every applied
  layer keyed by blob digest, so a cached layer folds into the MemFS
  tree without re-inflating the blob or re-parsing the tar;
- the dirty-set tracker: an inotify watcher (ctypes, Linux) with a
  portable mtime-walk delta fallback (``snapshot.walk.snapshot_delta``)
  accumulating changed paths between builds.

The resolved native/JAX runtime stays resident for free (the worker is
one process); the session records its identity so an ISA/ABI flip
invalidates rather than silently mixing routes.

Invalidation story (every reason labels
``makisu_session_invalidations_total``):

- ``flag_identity``: same context, different resolved build flags;
- ``isa_change``: the native ISA/ABI route moved under the process;
- ``ttl``: idle beyond ``MAKISU_TPU_SESSION_TTL`` seconds;
- ``lru``: evicted past ``MAKISU_TPU_SESSION_MAX`` sessions or the
  ``MAKISU_TPU_SESSION_MAX_MB`` resident-byte budget (accounted on
  ``/healthz``);
- ``explicit``: ``POST /sessions/invalidate`` or a manager reset.

Correctness contract: a session only ever REPLAYS state that is a pure
function of inputs that provably didn't change (stat signatures with
the racily-clean discipline, digest-keyed layer headers), so image
digests are byte-identical to a cold build at every point — asserted
by the dirty-set tests and the ``northstar_incremental`` bench.
"""

from __future__ import annotations

import contextvars
import ctypes
import ctypes.util
import hashlib
import json
import os
import struct
import threading
import time

import importlib

from makisu_tpu.utils import ledger, metrics
from makisu_tpu.utils import logging as log

# The snapshot package re-exports the walk FUNCTION under the module's
# own name; resolve the MODULE explicitly.
walk_mod = importlib.import_module("makisu_tpu.snapshot.walk")

# Session metric names live in the utils/metrics.py registry (the
# `check` metric-registry invariant: one spelling per series).
SESSION_HITS = metrics.SESSION_HITS
SESSION_INVALIDATIONS = metrics.SESSION_INVALIDATIONS
SESSION_RESIDENT_BYTES = metrics.SESSION_RESIDENT_BYTES

# Rough per-unit resident-byte estimates for the /healthz accounting.
# Exact sizes would need sys.getsizeof walks per build; the budget is a
# safety cap, not a ledger, so stable estimates beat precise churn.
_BYTES_PER_LAYER_ENTRY = 600   # TarInfo + path strings
_BYTES_PER_CONTENT_ID = 200    # statcache entry (key + stat quadruple)
_BYTES_PER_MEMO = 160          # scan-memo key/value

# Scan-memo entries kept per session: keys are (source, checksum_in);
# upstream cache-ID churn mints new keys, so stale ones age out by cap.
_SCAN_MEMO_KEEP = 512


def enabled() -> bool:
    """Resident sessions are on by default (a session that is never
    reused costs one dict entry); MAKISU_TPU_SESSION=0 disables."""
    return os.environ.get("MAKISU_TPU_SESSION", "1") == "1"


def session_ttl() -> float:
    try:
        return float(os.environ.get("MAKISU_TPU_SESSION_TTL", "3600"))
    except ValueError:
        return 3600.0


def max_sessions() -> int:
    try:
        return int(os.environ.get("MAKISU_TPU_SESSION_MAX", "8"))
    except ValueError:
        return 8


def max_resident_bytes() -> int:
    try:
        mb = float(os.environ.get("MAKISU_TPU_SESSION_MAX_MB", "512"))
    except ValueError:
        mb = 512.0
    return int(mb * 1e6)


def max_watches() -> int:
    try:
        return int(os.environ.get("MAKISU_TPU_SESSION_MAX_WATCHES",
                                  "8192"))
    except ValueError:
        return 8192


# This build's residency state for the history record's ``warm_mode``
# label: "resident" (session reused with an exact dirty set), "fresh"
# (new session: first build of this context/identity), "rescan"
# (session reused but dirty knowledge was lost — full re-scan), "off"
# (sessions disabled or bypassed), "none" (non-build command).
_warm_mode: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "makisu_session_warm_mode", default="none")


def warm_mode() -> str:
    return _warm_mode.get()


def set_warm_mode(label: str) -> None:
    _warm_mode.set(label)


def _isa_identity() -> str:
    """The native route identity a session was built under. Only what
    is ALREADY resolved: sessions must not force a native-library load
    (cheap commands never pay `make`)."""
    from makisu_tpu import native
    return native.isa_route_if_resolved() or "unresolved"


def identity_from_build_args(args, storage_dir: str,
                             gzip_backend_id: str) -> str:
    """Stable digest of the resolved flags that shape build identity
    for one context. Anything here that moves mints a new session
    (reason=flag_identity) — mixing, say, two hashers' warm state
    would be silently wrong."""
    ident = {
        "context": os.path.abspath(args.context),
        "root": os.path.abspath(args.root),
        "storage": os.path.abspath(storage_dir),
        "dockerfile": os.path.abspath(
            args.file or os.path.join(args.context, "Dockerfile")),
        "hasher": args.hasher,
        "gzip_backend_id": gzip_backend_id,
        "modifyfs": bool(args.modifyfs),
        "commit": args.commit,
        "target": args.target,
        "build_args": sorted(args.build_arg),
        "blacklist": sorted(args.blacklist),
    }
    blob = json.dumps(ident, sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


# -- inotify watcher --------------------------------------------------------

_IN_ACCESS = 0x00000001
_IN_MODIFY = 0x00000002
_IN_ATTRIB = 0x00000004
_IN_CLOSE_WRITE = 0x00000008
_IN_MOVED_FROM = 0x00000040
_IN_MOVED_TO = 0x00000080
_IN_CREATE = 0x00000100
_IN_DELETE = 0x00000200
_IN_DELETE_SELF = 0x00000400
_IN_MOVE_SELF = 0x00000800
_IN_ISDIR = 0x40000000
_IN_Q_OVERFLOW = 0x00004000
_IN_IGNORED = 0x00008000
_IN_NONBLOCK = 0x00000800  # O_NONBLOCK on linux
_IN_CLOEXEC = 0x00080000   # O_CLOEXEC on linux

_WATCH_MASK = (_IN_MODIFY | _IN_ATTRIB | _IN_CLOSE_WRITE
               | _IN_MOVED_FROM | _IN_MOVED_TO | _IN_CREATE
               | _IN_DELETE | _IN_DELETE_SELF | _IN_MOVE_SELF)

_EVENT_HDR = struct.Struct("iIII")  # wd, mask, cookie, len


def _libc():
    name = ctypes.util.find_library("c")
    return ctypes.CDLL(name, use_errno=True) if name else None


class InotifyWatcher:
    """Recursive inotify watch over a context tree. Best-effort by
    design: any failure (no inotify, watch-limit ENOSPC, queue
    overflow, structural events that stale the wd→path map) flips
    ``healthy`` off and the session falls back to the mtime-walk
    delta. ``collect()`` drains pending events into a dirty-path set;
    ``resync()`` (after a build) re-registers watches so directories
    created between builds are covered going forward."""

    def __init__(self, root: str, blacklist: list[str]) -> None:
        self.root = root
        self.blacklist = list(blacklist)
        self.healthy = False
        self._fd = -1
        self._wd_paths: dict[int, str] = {}
        self._needs_resync = False
        self._libc = _libc()
        if self._libc is None or not hasattr(self._libc,
                                             "inotify_init1"):
            return
        fd = self._libc.inotify_init1(_IN_NONBLOCK | _IN_CLOEXEC)
        if fd < 0:
            return
        self._fd = fd
        self.healthy = self._add_watches()
        if not self.healthy:
            self.close()

    def _dirs(self) -> list[str]:
        """Directory list via a stat-free scandir descent (dirent type
        bits only): registering watches over a 100k-file tree must not
        pay a full per-file lstat walk."""
        from makisu_tpu.utils import pathutils
        dirs = [self.root]
        stack = [self.root]
        limit = max_watches()
        try:
            while stack:
                cur = stack.pop()
                with os.scandir(cur) as it:
                    for entry in it:
                        if not entry.is_dir(follow_symlinks=False):
                            continue
                        if pathutils.is_descendant_of_any(
                                entry.path, self.blacklist):
                            continue
                        dirs.append(entry.path)
                        if len(dirs) > limit:
                            return dirs  # caller sees > cap and bails
                        stack.append(entry.path)
        except OSError:
            return []
        return dirs

    def _add_watches(self) -> bool:
        dirs = self._dirs()
        if not dirs or len(dirs) > max_watches():
            return False
        for path in dirs:
            wd = self._libc.inotify_add_watch(
                self._fd, path.encode(), _WATCH_MASK)
            if wd < 0:
                return False  # ENOSPC / vanished dir: fall back whole
            self._wd_paths[wd] = path
        return True

    def collect(self) -> set[str] | None:
        """Drain events into dirty paths. ``None`` means knowledge was
        lost (overflow, read error, structural staleness) — callers
        must fall back to a full re-scan."""
        if not self.healthy:
            return None
        dirty: set[str] = set()
        structural = False
        while True:
            try:
                buf = os.read(self._fd, 65536)
            except BlockingIOError:
                break
            except OSError:
                self.healthy = False
                return None
            if not buf:
                break
            off = 0
            while off + _EVENT_HDR.size <= len(buf):
                wd, mask, _cookie, nlen = _EVENT_HDR.unpack_from(
                    buf, off)
                name = buf[off + _EVENT_HDR.size:
                           off + _EVENT_HDR.size + nlen].rstrip(b"\0")
                off += _EVENT_HDR.size + nlen
                if mask & _IN_Q_OVERFLOW:
                    self.healthy = False
                    return None
                base = self._wd_paths.get(wd)
                if mask & _IN_IGNORED:
                    self._wd_paths.pop(wd, None)
                    structural = True
                    continue
                if base is None:
                    continue
                path = (os.path.join(base, name.decode(
                    errors="surrogateescape")) if name else base)
                dirty.add(path)
                if mask & (_IN_ISDIR | _IN_DELETE_SELF
                           | _IN_MOVE_SELF):
                    # A directory appeared/vanished/moved: its
                    # subtree's future events are unreliable until
                    # watches re-register (resync after the build).
                    # The dir itself is dirty, which forces the
                    # containing source to re-walk — correctness holds
                    # without per-event watch surgery.
                    structural = True
        if structural:
            self._needs_resync = True
        return dirty

    def resync(self) -> None:
        """Re-register watches after structural churn (directory
        create/delete/rename staled the wd→path map or left subtrees
        unwatched). NO-OP on the steady path: without a structural
        event no new directories can exist, so a stable tree pays
        nothing per build — the per-build full-tree walk this replaces
        was itself a warm-floor term at 100k files."""
        if not self.healthy or not self._needs_resync:
            return
        for wd in list(self._wd_paths):
            self._libc.inotify_rm_watch(self._fd, wd)
        self._wd_paths.clear()
        self._needs_resync = False
        self.healthy = self._add_watches()

    def close(self) -> None:
        if self._fd >= 0:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = -1
        self.healthy = False


# -- the session ------------------------------------------------------------


class BuildSession:
    """One context's resident warm state. Single-writer: the manager
    hands a session to at most one build at a time (concurrent builds
    of the same context bypass with reason=busy)."""

    def __init__(self, context_dir: str, identity: str) -> None:
        self.context_dir = context_dir
        self.identity = identity
        self.isa = _isa_identity()
        self.created_mono = time.monotonic()
        self.last_used_mono = self.created_mono
        self.builds = 0
        self.hits = 0
        self.busy = False
        # Resident state.
        self.content_ids = None  # adopted from the first BuildContext
        self.scan_memo: dict[tuple[str, int],
                             tuple[int, int, int]] = {}
        # Applied-layer op streams keyed by (applied-chain, digest):
        # valid only at the exact chain position they were recorded at
        # (builder/node.py holds the correctness argument).
        self.layer_replay: dict[tuple[str, str], list] = {}
        self._layer_entry_count = 0
        self.snapshot: walk_mod.TreeSnapshot | None = None
        self.watcher: InotifyWatcher | None = None
        self.pending_dirty: set[str] = set()
        # True iff the dirty set provably covers every change since the
        # last successful build; False forces a full re-scan.
        self.exact = False
        self._ignore_sig = None  # .dockerignore content hash
        self._walk_blacklist: list[str] = []
        # Whether arming expensive tracking (the full-walk baseline)
        # is worth it: set per build from resident_process / repeat use.
        self._resident_hint = False

    # -- accounting --

    def resident_bytes(self) -> int:
        n = self._layer_entry_count * _BYTES_PER_LAYER_ENTRY
        n += len(self.scan_memo) * _BYTES_PER_MEMO
        if self.content_ids is not None:
            n += (len(getattr(self.content_ids, "_entries", None) or ())
                  * _BYTES_PER_CONTENT_ID)
        if self.snapshot is not None:
            n += self.snapshot.approx_bytes()
        return n

    def stats(self) -> dict:
        now = time.monotonic()
        return {
            "context": self.context_dir,
            "identity": self.identity,
            "isa": self.isa,
            "builds": self.builds,
            "hits": self.hits,
            "resident_bytes": self.resident_bytes(),
            "layers_cached": len(self.layer_replay),
            "scan_memo_entries": len(self.scan_memo),
            "dirty_pending": len(self.pending_dirty),
            "dirty_exact": self.exact,
            "watcher": ("inotify" if self.watcher is not None
                        and self.watcher.healthy else "mtime-walk"),
            "age_seconds": round(now - self.created_mono, 3),
            "idle_seconds": round(now - self.last_used_mono, 3),
            "busy": self.busy,
        }

    # -- dirty tracking --

    def _ignore_signature(self):
        path = os.path.join(self.context_dir, ".dockerignore")
        try:
            with open(path, "rb") as f:
                return hashlib.sha256(f.read()).hexdigest()
        except OSError:
            return None

    def poll_changes(self) -> set[str]:
        """Accumulate changes since the last poll/build into
        ``pending_dirty`` and return the signature-confirmed NEW dirt
        from this poll (what a watch loop triggers on). Watcher events
        when healthy; one mtime-walk delta otherwise.

        Knowledge loss — watcher overflow/death, a failed delta walk,
        or no baseline at all — NEVER goes silent: the session turns
        inexact, the whole context is flagged dirty once (so the next
        build re-scans everything and a watch loop rebuilds), and a
        fresh walk baseline is seeded so tracking resumes."""
        if self.watcher is not None and self.watcher.healthy:
            got = self.watcher.collect()
            if got is not None:
                self.pending_dirty |= got
                # New dirs appeared? Register their watches BEFORE the
                # caller scans, so edits inside them during the build
                # are evented (no-op without structural churn).
                self.watcher.resync()
                if self.watcher.healthy:
                    return got
            # Overflow / read error / resync failure: the watcher is
            # dead — release its fd + kernel watches (a long-lived
            # worker must not pin inotify limits on corpses) and fall
            # through to re-seed the walk baseline.
            self.watcher.close()
        if self.snapshot is not None:
            try:
                self.snapshot, delta = walk_mod.snapshot_delta(
                    self.snapshot, self._walk_blacklist)
            except OSError:
                self.snapshot = None
                self.exact = False
                self.pending_dirty.add(self.context_dir)
                return {self.context_dir}
            self.pending_dirty |= delta.dirty
            return delta.real_dirty
        # No baseline: what changed since the last certified point is
        # unknowable — flag everything once and re-baseline. The
        # baseline walk (a full lstat pass) only runs when residency
        # can pay it back: a resident process, or an in-process repeat
        # build. A one-shot CLI build on a watcher-less host skips it
        # — it would be a 100k-file walk armed for a process about to
        # exit.
        self.exact = False
        self.pending_dirty.add(self.context_dir)
        if self._resident_hint:
            try:
                self.snapshot = walk_mod.snapshot_tree(
                    self.context_dir, self._walk_blacklist)
            except OSError:
                self.snapshot = None
        return {self.context_dir}

    # -- build lifecycle --

    def begin_build(self, ctx, resident_process: bool = False) -> str:
        """Arm ``ctx`` with this session's resident state. Returns the
        warm mode this build runs under ("resident" | "rescan").
        ``resident_process`` (worker / --watch) additionally defers
        statcache persistence to a background thread — a one-shot CLI
        process must keep the synchronous save or it may exit before
        the write lands."""
        self.builds += 1
        self.last_used_mono = time.monotonic()
        self._resident_hint = resident_process or self.builds >= 2
        self._walk_blacklist = [
            p for p in (list(ctx.base_blacklist)
                        + [ctx.image_store.root])
            if p != ctx.context_dir]
        # The tracker must exist BEFORE this build's scan reads any
        # file: an edit landing mid-build (after the scan passed it)
        # must surface in the NEXT build's dirty set — watcher events
        # queue in the kernel; the walk baseline below is captured
        # pre-scan so the next delta re-examines anything that moved
        # after it. A baseline taken after the build would absorb
        # mid-build edits and replay a stale scan memo.
        if self.watcher is None:
            self.watcher = InotifyWatcher(self.context_dir,
                                          self._walk_blacklist)
            if not self.watcher.healthy:
                self.watcher.close()
        self.poll_changes()
        # .dockerignore governs which paths enter cache identity but
        # lives OUTSIDE the per-source subtrees, so the scan memo can't
        # see it change through the dirty containment check — hash it
        # every build and drop the memo on any change.
        ignore_sig = self._ignore_signature()
        if ignore_sig != self._ignore_sig:
            if self._ignore_sig is not None or ignore_sig is not None:
                self.scan_memo.clear()
            self._ignore_sig = ignore_sig
        # Adopt or install the resident content-ID cache.
        if self.content_ids is None:
            self.content_ids = ctx.content_ids
        else:
            ctx.content_ids = self.content_ids
        begin = getattr(self.content_ids, "begin_build", None)
        if begin is not None:
            begin()
        # Resident process: the statcache's disk copy is durability
        # only — persist it off the build's critical path.
        if resident_process:
            self.content_ids.defer_save = True
        mode = "resident" if self.exact else "rescan"
        ctx.session = self
        ctx.dirty_paths = frozenset(self.pending_dirty)
        ctx.dirty_exact = self.exact
        if self.exact:
            self.hits += 1
            metrics.counter_add(SESSION_HITS)
        log.info("build session %s: mode=%s dirty=%d builds=%d",
                 self.identity, mode, len(self.pending_dirty),
                 self.builds)
        return mode

    def finish_build(self, ctx, ok: bool) -> None:
        self.last_used_mono = time.monotonic()
        if ok:
            # Everything dirty was consumed by this build's scan.
            self.pending_dirty.clear()
            if self.watcher is not None and self.watcher.healthy:
                # Mid-build edits are drained AND kept pending: the
                # scan may have read a file before the racing write
                # landed — one conservative extra re-hash, never a
                # stale identity. Collect runs BEFORE resync so a
                # raced structural event (new dir) triggers the watch
                # rebuild.
                raced = self.watcher.collect()
                self.watcher.resync()
                if raced is None or not self.watcher.healthy:
                    # Watcher died at the finish line: the next
                    # begin's poll flags the context and re-seeds a
                    # walk baseline.
                    self.watcher.close()
                    self.snapshot = None
                    self.exact = False
                else:
                    self.pending_dirty |= raced
                    self.exact = True
            else:
                # mtime-walk fallback: the baseline captured at
                # begin_build — BEFORE this build's scan — is the
                # certification point; the next delta re-examines
                # anything that moved after it, including mid-build
                # edits.
                self.exact = self.snapshot is not None
        else:
            # A failed build may have consumed part of the dirty set
            # before dying; only a full re-scan re-certifies it.
            self.exact = False
            self.snapshot = None
            self.pending_dirty.clear()
            self.scan_memo.clear()
        # The per-build context must not leak a dead session reference.
        ctx.session = None
        ctx.dirty_paths = frozenset()
        ctx.dirty_exact = False

    # -- memo surfaces (called via ctx by steps/memfs/node) --

    def scan_lookup(self, source: str, checksum_in: int):
        return self.scan_memo.get((source, checksum_in))

    def scan_store(self, source: str, checksum_in: int,
                   checksum_out: int, files: int, nbytes: int) -> None:
        if len(self.scan_memo) >= _SCAN_MEMO_KEEP:
            # Insertion-order eviction: stale (source, checksum) keys
            # from superseded chains age out first.
            self.scan_memo.pop(next(iter(self.scan_memo)))
        self.scan_memo[(source, checksum_in)] = (
            checksum_out, files, nbytes)

    def replay_lookup(self, key: tuple[str, str]):
        return self.layer_replay.get(key)

    def replay_store(self, key: tuple[str, str],
                     entries: list) -> None:
        if key in self.layer_replay:
            return
        self.layer_replay[key] = entries
        self._layer_entry_count += len(entries)

    def evict_layers(self, keep_bytes: int) -> None:
        """Drop oldest layer memos until resident bytes fit."""
        while (self.layer_replay
               and self.resident_bytes() > keep_bytes):
            key, entries = next(iter(self.layer_replay.items()))
            del self.layer_replay[key]
            self._layer_entry_count -= len(entries)

    def close(self) -> None:
        if self.watcher is not None:
            self.watcher.close()
            self.watcher = None


# -- the manager ------------------------------------------------------------


class SessionManager:
    """Process-wide session registry with TTL/LRU/byte-budget
    eviction. One session per context path; acquire is non-blocking —
    a second concurrent build of the same context bypasses residency
    instead of serializing on it."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._sessions: dict[str, BuildSession] = {}
        self.invalidations: dict[str, int] = {}

    def _invalidate_locked(self, key: str, reason: str) -> None:
        session = self._sessions.pop(key, None)
        if session is None:
            return
        session.close()
        self.invalidations[reason] = \
            self.invalidations.get(reason, 0) + 1
        metrics.counter_add(SESSION_INVALIDATIONS, reason=reason)
        ledger.record("session", session.context_dir, "invalidated",
                      reason=reason, builds=session.builds,
                      resident_bytes=session.resident_bytes())
        log.info("build session invalidated: %s (%s)",
                 session.context_dir, reason)

    def _publish_bytes_locked(self) -> None:
        total = sum(s.resident_bytes()
                    for s in self._sessions.values())
        metrics.global_registry().gauge_set(SESSION_RESIDENT_BYTES,
                                            total)

    def acquire(self, context_dir: str,
                identity: str) -> tuple["BuildSession | None", str]:
        """Lease the context's session for one build. Returns
        ``(session, verdict)`` where verdict is one of ``hit`` (a live
        session was reused), ``miss`` (a new session was created), or
        ``busy`` (another build holds it — caller proceeds without
        residency)."""
        context_dir = os.path.abspath(context_dir)
        key = os.path.realpath(context_dir)
        now = time.monotonic()
        with self._mu:
            session = self._sessions.get(key)
            if session is not None:
                if session.busy:
                    return None, "busy"
                if session.identity != identity:
                    self._invalidate_locked(key, "flag_identity")
                    session = None
                elif session.isa != _isa_identity():
                    self._invalidate_locked(key, "isa_change")
                    session = None
                elif now - session.last_used_mono > session_ttl():
                    self._invalidate_locked(key, "ttl")
                    session = None
            verdict = "hit" if session is not None else "miss"
            if session is None:
                session = BuildSession(context_dir, identity)
                self._sessions[key] = session
                # Count-based LRU: evict the stalest idle session.
                while len(self._sessions) > max(1, max_sessions()):
                    victims = sorted(
                        ((s.last_used_mono, k)
                         for k, s in self._sessions.items()
                         if k != key and not s.busy))
                    if not victims:
                        break
                    self._invalidate_locked(victims[0][1], "lru")
            session.busy = True
            self._publish_bytes_locked()
        return session, verdict

    def release(self, session: BuildSession) -> None:
        key = os.path.realpath(session.context_dir)
        budget = max_resident_bytes()
        with self._mu:
            session.busy = False
            # Byte budget: first shrink the releasing session's layer
            # memo, then evict whole idle sessions oldest-first.
            total = sum(s.resident_bytes()
                        for s in self._sessions.values())
            if total > budget:
                session.evict_layers(
                    max(0, budget - (total - session.resident_bytes())))
            while (sum(s.resident_bytes()
                       for s in self._sessions.values()) > budget
                   and len(self._sessions) > 1):
                victims = sorted(
                    ((s.last_used_mono, k)
                     for k, s in self._sessions.items()
                     if k != key and not s.busy))
                if not victims:
                    break
                self._invalidate_locked(victims[0][1], "lru")
            self._publish_bytes_locked()

    def peek(self, context_dir: str) -> "BuildSession | None":
        """The context's live session, if any — no lease, no
        invalidation checks (the watch loop polls change state through
        it between builds)."""
        key = os.path.realpath(os.path.abspath(context_dir))
        with self._mu:
            return self._sessions.get(key)

    def invalidate(self, context_dir: str = "") -> int:
        """Explicit invalidation (the worker's POST endpoint). Empty
        context drops every non-busy session; returns the count."""
        dropped = 0
        with self._mu:
            if context_dir:
                keys = [os.path.realpath(os.path.abspath(context_dir))]
            else:
                keys = list(self._sessions)
            for key in keys:
                session = self._sessions.get(key)
                if session is None or session.busy:
                    continue
                self._invalidate_locked(key, "explicit")
                dropped += 1
            self._publish_bytes_locked()
        return dropped

    def stats(self) -> dict:
        """The ``/healthz`` sessions section + ``GET /sessions``."""
        with self._mu:
            sessions = [s.stats() for s in self._sessions.values()]
            # Copied under the lock: a concurrent first-of-its-kind
            # invalidation reason would otherwise mutate the dict mid-
            # iteration and 500 a health probe.
            invalidations = dict(self.invalidations)
        sessions.sort(key=lambda s: s["context"])
        return {
            "count": len(sessions),
            "resident_bytes": sum(s["resident_bytes"]
                                  for s in sessions),
            "hits": sum(s["hits"] for s in sessions),
            "invalidations": dict(sorted(invalidations.items())),
            "max_sessions": max_sessions(),
            "max_resident_bytes": max_resident_bytes(),
            "ttl_seconds": session_ttl(),
            "sessions": sessions,
        }

    def reset(self) -> None:
        """Drop everything (tests)."""
        with self._mu:
            for session in self._sessions.values():
                session.close()
            self._sessions.clear()
            self.invalidations.clear()
            self._publish_bytes_locked()


_manager = SessionManager()

# Context-bound manager override: a WorkerServer binds ITS OWN
# SessionManager around every build it runs, so multiple in-process
# workers (the fleet loadgen topology, and any test standing up a
# 3-worker fleet in one interpreter) model real machines — each
# worker's resident sessions, /sessions rows, and affinity signal are
# its own, exactly as they would be across separate hosts. Standalone
# CLI builds and --watch keep the process-global manager.
_bound_manager: "contextvars.ContextVar[SessionManager | None]" = \
    contextvars.ContextVar("makisu_session_manager", default=None)


def bind_manager(mgr: SessionManager):
    """Bind ``mgr`` as the current context's session manager (threads
    the build spawns inherit it via ``contextvars.copy_context``).
    Returns a reset token."""
    return _bound_manager.set(mgr)


def reset_manager(token) -> None:
    _bound_manager.reset(token)


def manager() -> SessionManager:
    return _bound_manager.get() or _manager
