"""Chunk-addressed session snapshots: durable, portable warm state.

A resident build session (worker/session.py) is the repo's biggest
perf asset — and it dies with the process. This module serializes a
session's memos into **shards** stored through the existing chunk CAS
(cache/chunks.py), indexed by a small JSON **recipe** under
``<storage>/serve/snapshots/<key>.json``:

- ``scan``: the context-scan memo — (source, checksum_in) →
  (checksum_out, files, bytes) transitions;
- ``stat/<n>``: the stat/content-ID cache entries for this context,
  bucketed by rel-path hash so one touched file re-chunks one bucket,
  not 100k entries;
- ``walk/<n>``: the mtime-walk baseline's stat signatures, bucketed
  the same way — the certification point a restored session deltas
  against, so the snapshot→restore gap is covered at exactly the trust
  level the live mtime-walk fallback already has;
- ``layer/<key>``: one shard per MemFS layer-replay memo entry, keyed
  by (applied-chain, digest) — content-addressed, so identical layers
  dedupe across sessions and workers for free.

Shard docs serialize deterministically (sorted keys), so an unchanged
shard hashes to the chunk it already has: ``finish_build`` checkpoints
in O(changed shards), and an idle session checkpoints for the cost of
a few ``exists`` stats. The recipe carries the full invalidation
story — portable flag identity, ISA route, capture time — and
:func:`try_restore` enforces it (``flag_identity`` / ``isa_change`` /
``stale``) before any shard byte is trusted, so a restored session's
digests stay byte-identical to a cold build.

Restored stat-cache entries keep their original ``hashed_at``
timestamps: the racily-clean discipline and the per-lookup stat
comparison apply to them unchanged, so a deliberately stale restored
entry re-stats and re-hashes — never replays.

The chunk fetch on restore rides :meth:`ChunkStore.ensure_available`,
i.e. the same fleet peer wire / ranged-pack path every other chunk
miss uses — which is what makes fleet **prewarm** one recipe POST: the
target stages the recipe and pulls the missing shard chunks from the
source worker before the build arrives.
"""

from __future__ import annotations

import hashlib
import json
import os
import tarfile
import time
import zlib

from makisu_tpu.utils import fileio, metrics
from makisu_tpu.utils import logging as log

SNAPSHOT_SCHEMA = "makisu-tpu.session-snapshot.v1"
SNAPSHOT_SUBDIR = os.path.join("serve", "snapshots")

# Rel-path hash buckets for the stat and walk shards: enough that one
# touched file re-serializes ~1/16th of a big table, few enough that an
# idle checkpoint's existence probe stays a handful of stats.
STAT_BUCKETS = 16
WALK_BUCKETS = 16

# TarInfo fields that round-trip through a layer shard. Offsets and
# sparse maps are stream-position state that replay never consults.
_TAR_FIELDS = ("name", "mode", "uid", "gid", "size", "mtime",
               "linkname", "uname", "gname", "devmajor", "devminor")


def snapshots_dir(storage_dir: str) -> str:
    return os.path.join(os.path.abspath(storage_dir), SNAPSHOT_SUBDIR)


def snap_key(context_dir: str, portable_identity: str) -> str:
    """Recipe filename key: one recipe per (context, portable flag
    identity) — a checkpoint overwrites its predecessor atomically."""
    blob = (os.path.realpath(os.path.abspath(context_dir))
            + "\n" + portable_identity).encode()
    return hashlib.sha256(blob).hexdigest()


def _bucket(rel: str, buckets: int) -> int:
    return zlib.crc32(rel.encode("utf-8", "surrogateescape")) % buckets


def _dumps(doc) -> bytes:
    return json.dumps(doc, sort_keys=True,
                      separators=(",", ":")).encode()


# -- TarInfo / layer-entry round-trip ---------------------------------------


def _tarinfo_to_doc(hdr: tarfile.TarInfo) -> dict:
    doc = {f: getattr(hdr, f) for f in _TAR_FIELDS}
    doc["type"] = hdr.type.decode("latin-1")
    if hdr.pax_headers:
        doc["pax"] = {str(k): str(v)
                      for k, v in hdr.pax_headers.items()}
    return doc


def _tarinfo_from_doc(doc: dict) -> tarfile.TarInfo:
    hdr = tarfile.TarInfo()
    for f in _TAR_FIELDS:
        if f in doc:
            setattr(hdr, f, doc[f])
    hdr.type = str(doc.get("type", "0")).encode("latin-1")
    pax = doc.get("pax")
    if isinstance(pax, dict):
        hdr.pax_headers = {str(k): str(v) for k, v in pax.items()}
    return hdr


def _entries_to_doc(entries: list) -> list:
    from makisu_tpu.snapshot.layer import ContentEntry, WhiteoutEntry
    out = []
    for e in entries:
        if isinstance(e, WhiteoutEntry):
            out.append({"wh": e.deleted})
        elif isinstance(e, ContentEntry):
            out.append({"src": e.src, "dst": e.dst,
                        "hdr": _tarinfo_to_doc(e.hdr)})
        else:
            raise ValueError(f"unknown layer entry {type(e)!r}")
    return out


def _entries_from_doc(doc: list) -> list:
    from makisu_tpu.snapshot.layer import ContentEntry, WhiteoutEntry
    out = []
    for row in doc:
        if "wh" in row:
            out.append(WhiteoutEntry(str(row["wh"])))
        else:
            out.append(ContentEntry(str(row["src"]), str(row["dst"]),
                                    _tarinfo_from_doc(row["hdr"])))
    return out


# -- the store --------------------------------------------------------------


class SnapshotStore:
    """One storage dir's snapshot plane: recipes under
    ``serve/snapshots/``, shard bytes in the shared chunk CAS."""

    def __init__(self, storage_dir: str) -> None:
        self.storage_dir = os.path.abspath(storage_dir)
        self.dir = snapshots_dir(storage_dir)
        self._chunks = None

    def chunk_store(self):
        if self._chunks is None:
            from makisu_tpu.cache.chunks import (ChunkStore,
                                                 register_serving_store)
            self._chunks = ChunkStore(
                os.path.join(self.storage_dir, "chunks"))
            # Snapshot shards must be fetchable by fleet siblings over
            # GET /chunks/<fp> (the prewarm pull), even when no build
            # ever attached chunk dedup for this storage (cpu-hasher
            # builds write snapshots too). Registration is idempotent
            # per CAS root, and the worker's served-root scoping still
            # gates which in-process sibling may serve it.
            register_serving_store(self._chunks)
        return self._chunks

    def recipe_path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.json")

    def write_recipe(self, recipe: dict) -> str:
        key = snap_key(recipe["context"], recipe["portable_identity"])
        os.makedirs(self.dir, exist_ok=True)
        fileio.write_json_atomic(self.recipe_path(key), recipe)
        return key

    def load(self, context_dir: str,
             portable_identity: str) -> dict | None:
        return self._read(self.recipe_path(
            snap_key(context_dir, portable_identity)))

    def load_for_context(self, context_dir: str) -> dict | None:
        """Newest recipe for a context regardless of identity — the
        prewarm pull path, where the front door knows the context key
        but not the resolved flag identity."""
        key = os.path.realpath(os.path.abspath(context_dir))
        best = None
        try:
            names = os.listdir(self.dir)
        except OSError:
            return None
        for name in names:
            if not name.endswith(".json") or name.endswith(".tmp"):
                continue
            doc = self._read(os.path.join(self.dir, name))
            if doc is None or doc.get("context") != key:
                continue
            if best is None or (doc.get("saved_at", 0)
                                > best.get("saved_at", 0)):
                best = doc
        return best

    @staticmethod
    def _read(path: str) -> dict | None:
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(doc, dict) \
                or doc.get("schema") != SNAPSHOT_SCHEMA \
                or not isinstance(doc.get("shards"), dict):
            return None
        return doc

    def shard_plan(self, recipe: dict) -> list[tuple[int, int, str]]:
        """The recipe's chunk plan in ``ensure_available`` shape."""
        plan = []
        for row in recipe.get("shards", {}).values():
            plan.append((0, int(row.get("bytes", 0)),
                         str(row.get("chunk", ""))))
        return plan

    def stage(self, recipe: dict) -> tuple[bool, str]:
        """Adopt a foreign recipe (fleet prewarm push): persist it
        locally and pull any missing shard chunks over the peer wire.
        Returns ``(ok, reason)`` — a failed stage leaves no recipe
        behind, so a later restore attempt can't trust a plan whose
        bytes never arrived."""
        if not isinstance(recipe, dict) \
                or recipe.get("schema") != SNAPSHOT_SCHEMA \
                or not isinstance(recipe.get("shards"), dict) \
                or not recipe.get("context") \
                or not recipe.get("portable_identity"):
            return False, "schema"
        plan = self.shard_plan(recipe)
        if not all(h and len(h) == 64 for _, _, h in plan):
            return False, "schema"
        if not self.chunk_store().ensure_available(plan):
            return False, "chunks_unavailable"
        self.write_recipe(recipe)
        return True, ""


# -- checkpoint write -------------------------------------------------------

# Watcher-mode sessions keep a dedicated persistence baseline (the
# live session needs no walk at all); it refreshes once this many
# watcher-observed dirty paths accumulate, bounding the restore-time
# over-dirtying a stale baseline costs to one bounded re-scan.
BASELINE_REFRESH_PATHS = 4096


def _layer_shard_name(key: tuple) -> str:
    chain, digest = key
    return "layer/" + hashlib.sha256(
        f"{chain}:{digest}".encode()).hexdigest()[:32]


def write_snapshot(session, storage_dir: str) -> dict | None:
    """Checkpoint one session into the chunk CAS. Incremental: shards
    whose dirty flag is clear carry their previous chunk forward
    without re-serializing; re-serialized shards that hash to an
    existing chunk skip the put. Never raises — a checkpoint that
    cannot land costs durability, not the build."""
    try:
        return _write_snapshot(session, storage_dir)
    except Exception as exc:  # noqa: BLE001 - advisory by contract
        metrics.counter_add(metrics.SESSION_SNAPSHOT_WRITES,
                            result="error")
        log.warning("session snapshot write failed for %s: %s",
                    session.context_dir, exc)
        return None


def _write_snapshot(session, storage_dir: str) -> dict | None:
    if not session.portable_identity:
        return None
    store = SnapshotStore(storage_dir)
    chunks = store.chunk_store()
    carried: dict[str, list] = dict(session._snap_shards)
    shards: dict[str, dict] = {}
    written = reused = 0

    def put_shard(name: str, doc) -> None:
        nonlocal written, reused
        blob = _dumps(doc)
        hex_digest = hashlib.sha256(blob).hexdigest()
        if chunks.cas.exists(hex_digest):
            reused += len(blob)
        else:
            chunks.put(hex_digest, blob)
            written += len(blob)
        shards[name] = {"chunk": hex_digest, "bytes": len(blob)}

    def carry(name: str) -> bool:
        row = carried.get(name)
        if not row:
            return False
        shards[name] = {"chunk": row["chunk"],
                        "bytes": row["bytes"]}
        return True

    # scan memo: one shard, rewritten only after scan_store/clear.
    if session._snap_scan_dirty or not carry("scan"):
        put_shard("scan", [[src, cin, out, files, nbytes]
                           for (src, cin), (out, files, nbytes)
                           in session.scan_memo.items()])
        session._snap_scan_dirty = False

    # stat/content-ID cache: bucketed by rel-path hash; only buckets
    # holding a mutated key re-serialize.
    cache = session.content_ids
    if cache is not None and hasattr(cache, "namespace_items"):
        mutated = cache.drain_mutations()
        dirty = ({_bucket(rel, STAT_BUCKETS) for rel in mutated}
                 if not session._snap_stat_all
                 else set(range(STAT_BUCKETS)))
        items = None
        for b in range(STAT_BUCKETS):
            name = f"stat/{b}"
            if b not in dirty and carry(name):
                continue
            if items is None:
                items = [{} for _ in range(STAT_BUCKETS)]
                for rel, entry in cache.namespace_items().items():
                    items[_bucket(rel, STAT_BUCKETS)][rel] = entry
            put_shard(name, items[b])
        session._snap_stat_all = False

    # walk baseline: the certification point a restored session deltas
    # against. mtime-walk sessions persist the live begin-build
    # baseline (already current); watcher sessions keep a dedicated
    # one, refreshed only when accumulated churn makes the restore-time
    # delta too conservative.
    baseline = session.snapshot
    if session.watcher is not None and session.watcher.healthy:
        if session._snap_baseline is None and baseline is not None:
            # A restored-then-watched session already holds a current
            # walk baseline (the restore-gap delta refreshed it) —
            # adopt it instead of paying a fresh walk.
            session._snap_baseline = baseline
        if (session._snap_baseline is None
                or session._snap_gap_paths > BASELINE_REFRESH_PATHS):
            import importlib
            # `makisu_tpu.snapshot` exports a *function* named walk
            # that shadows the submodule on a from-import.
            walk_mod = importlib.import_module(
                "makisu_tpu.snapshot.walk")
            baseline = walk_mod.snapshot_tree(
                session.context_dir, session._walk_blacklist)
            session._snap_baseline = baseline
            session._snap_gap_paths = 0
            session._snap_walk_all = True
        else:
            baseline = session._snap_baseline
    walk_doc = None
    if baseline is not None:
        walk_doc = {"root": baseline.root,
                    "captured_ns": baseline.captured_ns,
                    "est_bytes": baseline.est_bytes,
                    "fresh": sorted(baseline.fresh)}
        dirty = ({_bucket(p, WALK_BUCKETS)
                  for p in session._snap_walk_dirty}
                 if not session._snap_walk_all
                 else set(range(WALK_BUCKETS)))
        sigs = None
        for b in range(WALK_BUCKETS):
            name = f"walk/{b}"
            if b not in dirty and carry(name):
                continue
            if sigs is None:
                sigs = [{} for _ in range(WALK_BUCKETS)]
                for path, sig in baseline.sigs.items():
                    sigs[_bucket(path, WALK_BUCKETS)][path] = list(sig)
            put_shard(name, sigs[b])
        session._snap_walk_all = False
        session._snap_walk_dirty.clear()

    # layer-replay memo: one content-keyed shard per entry; carried
    # names ARE the dedup, and evicted memos simply drop out of the
    # recipe (their chunks age out of the CAS by LRU like any other).
    layer_index = {}
    for key, entries in session.layer_replay.items():
        name = _layer_shard_name(key)
        layer_index[name] = list(key)
        if not carry(name):
            put_shard(name, _entries_to_doc(entries))

    recipe = {
        "schema": SNAPSHOT_SCHEMA,
        "context": os.path.realpath(session.context_dir),
        "identity": session.identity,
        "portable_identity": session.portable_identity,
        "isa": session.isa,
        "ignore_sig": session._ignore_sig,
        "exact": bool(session.exact and walk_doc is not None),
        "builds": session.builds,
        "saved_at": time.time(),
        "pending_dirty": sorted(session.pending_dirty),
        "walk": walk_doc,
        "layer_keys": layer_index,
        "shards": shards,
    }
    store.write_recipe(recipe)
    session._snap_shards = {n: dict(r) for n, r in shards.items()}
    metrics.counter_add(metrics.SESSION_SNAPSHOT_WRITES, result="ok")
    if written:
        metrics.counter_add(metrics.SESSION_SNAPSHOT_CHUNK_BYTES,
                            written, result="written")
    if reused:
        metrics.counter_add(metrics.SESSION_SNAPSHOT_CHUNK_BYTES,
                            reused, result="reused")
    return recipe


# -- restore ----------------------------------------------------------------


def try_restore(context_dir: str, identity: str, storage_dir: str,
                portable_identity: str):
    """Rebuild a session from the local snapshot plane. Returns
    ``(session, "")`` on success, ``(None, "")`` when no recipe exists
    (a plain cold miss, not a failure), or ``(None, reason)`` on a
    refusal/error — the reasons mirror the live invalidation story, so
    a snapshot can never outlive the checks a resident session obeys."""
    store = SnapshotStore(storage_dir)
    recipe = store.load(context_dir, portable_identity)
    if recipe is None:
        # Identity-keyed miss: fall back to any recipe for the context
        # so identity drift refuses LOUDLY (flag_identity) instead of
        # silently rebuilding cold.
        recipe = store.load_for_context(context_dir)
        if recipe is None:
            return None, ""
    return restore_from_recipe(store, recipe, context_dir, identity,
                               portable_identity)


def restore_from_recipe(store: SnapshotStore, recipe: dict,
                        context_dir: str, identity: str,
                        portable_identity: str):
    from makisu_tpu.worker import session as session_mod
    key = os.path.realpath(os.path.abspath(context_dir))
    if recipe.get("context") != key:
        return None, "context_mismatch"
    if recipe.get("portable_identity") != portable_identity:
        return None, "flag_identity"
    if recipe.get("isa") != session_mod._isa_identity():
        return None, "isa_change"
    age = time.time() - float(recipe.get("saved_at", 0) or 0)
    if age > session_mod.session_ttl():
        return None, "stale"
    chunks = store.chunk_store()
    if not chunks.ensure_available(store.shard_plan(recipe)):
        return None, "chunks_unavailable"
    try:
        return _materialize(store, recipe, context_dir,
                            identity), ""
    except Exception as exc:  # noqa: BLE001 - never fail the build
        log.warning("session snapshot restore failed for %s: %s",
                    context_dir, exc)
        return None, "corrupt"


def _load_shard(chunks, recipe: dict, name: str):
    row = recipe["shards"].get(name)
    if row is None:
        return None
    return json.loads(chunks.get(str(row["chunk"])).decode())


def _materialize(store: SnapshotStore, recipe: dict,
                 context_dir: str, identity: str):
    import importlib

    from makisu_tpu.worker import session as session_mod
    walk_mod = importlib.import_module("makisu_tpu.snapshot.walk")
    chunks = store.chunk_store()
    session = session_mod.BuildSession(context_dir, identity)
    session.portable_identity = recipe["portable_identity"]
    session.builds = int(recipe.get("builds", 0) or 0)
    session._ignore_sig = recipe.get("ignore_sig")
    session.pending_dirty = {str(p) for p in
                             recipe.get("pending_dirty") or []}

    scan = _load_shard(chunks, recipe, "scan") or []
    for src, cin, out, files, nbytes in scan:
        session.scan_memo[(str(src), int(cin))] = (
            int(out), int(files), int(nbytes))

    stat_entries: dict[str, list] = {}
    for b in range(STAT_BUCKETS):
        shard = _load_shard(chunks, recipe, f"stat/{b}")
        if isinstance(shard, dict):
            stat_entries.update(shard)
    session._restored_stat_entries = stat_entries or None

    walk_doc = recipe.get("walk")
    if isinstance(walk_doc, dict) and recipe.get("exact"):
        sigs: dict[str, tuple] = {}
        for b in range(WALK_BUCKETS):
            shard = _load_shard(chunks, recipe, f"walk/{b}")
            if isinstance(shard, dict):
                for path, sig in shard.items():
                    sigs[str(path)] = tuple(sig)
        session.snapshot = walk_mod.TreeSnapshot(
            str(walk_doc.get("root", context_dir)),
            int(walk_doc.get("captured_ns", 0) or 0),
            sigs,
            {str(p) for p in walk_doc.get("fresh") or []},
            int(walk_doc.get("est_bytes", 0) or 0))
        session.exact = True
        session._gap_delta_pending = True

    layer_keys = recipe.get("layer_keys") or {}
    for name, key in layer_keys.items():
        doc = _load_shard(chunks, recipe, name)
        if doc is None or not isinstance(key, list) or len(key) != 2:
            continue
        session.replay_store((str(key[0]), str(key[1])),
                             _entries_from_doc(doc))

    # The restored shards ARE the last checkpoint: carry their chunks
    # forward so the first post-restore checkpoint is incremental too.
    session._snap_shards = {n: dict(r) for n, r
                            in recipe["shards"].items()}
    session._snap_scan_dirty = False
    session._snap_stat_all = True  # local cache may hold extra keys
    session._snap_walk_all = False
    session.restored = True
    session._restore_fresh = True
    return session
