"""Long-lived worker mode: build over a unix socket.

Reference: lib/client/ (MakisuClient {Ready, Build, Exit} over a unix
socket, client.go:36-191). The reference ships only the client; here the
worker server is included too, so CI systems can keep one warm process
(with its JAX kernels compiled) and feed it builds.
"""

from makisu_tpu.worker.client import (
    BuildInfo,
    PercentileStats,
    WorkerBuilds,
    WorkerClient,
    WorkerHealth,
)
from makisu_tpu.worker.server import WorkerServer

__all__ = ["BuildInfo", "PercentileStats", "WorkerBuilds",
           "WorkerClient", "WorkerHealth", "WorkerServer"]
